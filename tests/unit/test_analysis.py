"""ds_doctor tests — static graph/sharding/collective/config analysis.

Covers: the schema walk (did-you-mean, raw blocks, cross-field), the
jaxpr graph lint (one seeded true-positive per rule, zero false
positives on the known-good family fixtures), the collective deadlock
detector (record mode, cross-rank diff, chaos ``collective_mismatch``
tie-in), the repo self-lint (runs IN tier-1 — a regression cannot
merge), engine wiring (strict no-op without the block, fail_on
semantics), and the bin/ds_doctor + ds_report doctor CLIs against the
acceptance matrix.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.analysis import AnalysisError, AnalysisReport, Finding
from deepspeed_tpu.analysis.collectives import (CollectiveRecord,
                                                CollectiveRecorder,
                                                diff_sequences,
                                                record_collectives)
from deepspeed_tpu.analysis.doctor import run_doctor
from deepspeed_tpu.analysis.graph_lint import (batch_shape_map,
                                               diff_batch_shapes,
                                               lint_donation, lint_jaxpr,
                                               lint_sharding_plan)
from deepspeed_tpu.analysis.schema import walk_config
from deepspeed_tpu.analysis.selflint import lint_package, lint_source

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

pytestmark = pytest.mark.analysis

BASE_CFG = {"train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "steps_per_print": 0}


def _errors(findings):
    return [f for f in findings if f.severity == "error"]


# --------------------------------------------------------------------- schema
class TestSchemaPass:
    def test_good_config_zero_findings(self):
        findings, cfg = walk_config({**BASE_CFG, "bf16": {"enabled": True}},
                                    world_size=1)
        assert findings == [] and cfg is not None

    def test_subblock_typo_is_error_with_suggestion(self):
        findings, _ = walk_config({**BASE_CFG, "fp16": {"enabld": True}},
                                  world_size=1)
        [f] = _errors(findings)
        assert f.rule == "config/unknown-key" and f.citation == "fp16"
        assert "did you mean 'enabled'" in f.message

    def test_multiple_broken_blocks_all_reported(self):
        findings, cfg = walk_config(
            {**BASE_CFG, "fp16": {"enabld": True},
             "watchdog": {"windoww": 8}}, world_size=1)
        assert cfg is None
        assert {f.citation for f in _errors(findings)} == {"fp16", "watchdog"}

    def test_raw_block_typo_is_error(self):
        findings, _ = walk_config(
            {**BASE_CFG, "autotuning": {"tuner_typ": "random"}}, world_size=1)
        assert any(f.rule == "config/unknown-key"
                   and "tuner_type" in f.message for f in _errors(findings))

    def test_raw_block_typo_raises_at_parse_time(self):
        from deepspeed_tpu.runtime.config import DeepSpeedConfig

        with pytest.raises(ValueError, match="tuner_type"):
            DeepSpeedConfig({**BASE_CFG,
                             "autotuning": {"tuner_typ": "random"}},
                            world_size=1)

    def test_autotuning_key_set_pinned_to_dataclass(self):
        """RAW_BLOCK_KEYS cannot drift from AutotuningConfig's fields."""
        from deepspeed_tpu.autotuning.autotuner import AutotuningConfig
        from deepspeed_tpu.runtime.config import RAW_BLOCK_KEYS

        assert RAW_BLOCK_KEYS["autotuning"] == frozenset(
            AutotuningConfig.__dataclass_fields__)

    def test_cross_field_offload_param_needs_stage3(self):
        findings, _ = walk_config(
            {**BASE_CFG, "zero_optimization": {
                "stage": 1, "offload_param": {"device": "cpu"}}},
            world_size=1)
        [f] = [f for f in findings if f.rule == "config/cross-field"]
        assert f.severity == "error" and "offload_param" in f.citation

    def test_cross_field_watchdog_consistency_ignored(self):
        findings, _ = walk_config(
            {**BASE_CFG, "watchdog": {"enabled": False,
                                      "consistency_interval": 10}},
            world_size=1)
        assert any(f.severity == "warning" and "consistency_interval"
                   in f.citation for f in findings)

    def test_cross_field_monitor_fanout_nowhere(self):
        findings, _ = walk_config(
            {**BASE_CFG, "telemetry": {"enabled": True, "monitor": True}},
            world_size=1)
        assert any("fan-out goes nowhere" in f.message for f in findings)

    def test_block_models_pinned_to_deepspeed_config(self):
        """Every pydantic block DeepSpeedConfig builds must be covered by
        the schema pass's independent per-block walk — a new config block
        that forgets analysis/schema.py fails here, not silently."""
        from deepspeed_tpu.analysis.schema import _block_models
        from deepspeed_tpu.runtime.config import DeepSpeedConfig, MonitorConfig
        from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel

        cfg = DeepSpeedConfig(dict(BASE_CFG), world_size=1)
        covered = set(_block_models().values())
        for name, val in vars(cfg).items():
            if not isinstance(val, DeepSpeedConfigModel):
                continue
            if isinstance(val, MonitorConfig):
                # container: its tensorboard/wandb/csv_monitor interiors are
                # separate top-level blocks, each covered individually
                continue
            assert type(val) in covered, (
                f"DeepSpeedConfig.{name} ({type(val).__name__}) is missing "
                "from analysis.schema._block_models — add it so the schema "
                "pass validates the block independently")

    def test_invalid_value_is_error(self):
        findings, cfg = walk_config(
            {**BASE_CFG, "watchdog": {"on_timeout": "abort"}}, world_size=1)
        assert cfg is None
        [f] = _errors(findings)
        assert f.rule == "config/invalid-value" and f.citation == "watchdog"
        assert "on_timeout" in f.message

    def test_config_model_did_you_mean_direct(self):
        from deepspeed_tpu.runtime.config import FP16Config

        with pytest.raises(ValueError, match="did you mean 'enabled'"):
            FP16Config(enabld=True)


# ---------------------------------------------------------------- graph lint
class TestGraphLint:
    def _mats(self, n=512):
        p = {"w": jax.ShapeDtypeStruct((n, n), jnp.bfloat16)}
        x = jax.ShapeDtypeStruct((64, n), jnp.bfloat16)
        return p, x

    def test_fp32_matmul_under_bf16_is_error(self):
        p, x = self._mats()

        def f(params, inp):
            return (inp.astype(jnp.float32)
                    @ params["w"].astype(jnp.float32)).sum()

        [f1] = lint_jaxpr(jax.make_jaxpr(f)(p, x), train_dtype=jnp.bfloat16,
                          min_promote_elements=1024)
        assert f1.rule == "graph/dtype-promotion" and f1.severity == "error"
        assert "dot_general" in f1.citation and "float32" in f1.message

    def test_bf16_matmul_clean(self):
        p, x = self._mats()

        def f(params, inp):
            # loss-path fp32 on the SCALAR is fine (below the size floor)
            return (inp @ params["w"]).sum().astype(jnp.float32)

        assert lint_jaxpr(jax.make_jaxpr(f)(p, x), train_dtype=jnp.bfloat16,
                          min_promote_elements=1024) == []

    def test_fp32_config_allows_fp32_matmul(self):
        p, x = self._mats()
        f = lambda params, inp: (inp.astype(jnp.float32)
                                 @ params["w"].astype(jnp.float32)).sum()
        assert lint_jaxpr(jax.make_jaxpr(f)(p, x), train_dtype=jnp.float32,
                          min_promote_elements=1024) == []

    def test_weak_scalar_input_flagged(self):
        f = lambda x, s: (x * s).sum()
        closed = jax.make_jaxpr(f)(jnp.ones((4, 4), jnp.bfloat16), 2.0)
        fs = lint_jaxpr(closed, train_dtype=jnp.bfloat16)
        assert [x.rule for x in fs] == ["graph/weak-scalar-input"]

    def test_donation_lint(self):
        state = {"m": jnp.zeros((256, 256), jnp.float32)}
        [f] = lint_donation((state, state), donate_argnums=(0,),
                            min_bytes=1024)
        assert f.rule == "graph/missing-donation" and f.citation == "arg[1]"
        assert lint_donation((state,), donate_argnums=(0,),
                             min_bytes=1024) == []

    def test_sharding_lint_flags_indivisible_leaf(self, mesh8):
        from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
        from deepspeed_tpu.runtime.zero.partition import plan_sharding

        shapes = {"odd": jax.ShapeDtypeStruct((10_001,), jnp.float32),
                  "even": jax.ShapeDtypeStruct((4096, 4), jnp.float32)}
        plan = plan_sharding(shapes, mesh8,
                             zero_config=DeepSpeedZeroConfig(stage=2))
        fs = lint_sharding_plan(plan, shapes, min_elements=1000)
        assert [f.rule for f in fs] == ["sharding/replicated-large-array"]
        assert "odd" in fs[0].message

    def test_batch_shape_diff(self):
        first = batch_shape_map({"input_ids": np.zeros((8, 32))})
        assert diff_batch_shapes(first, {"input_ids": np.zeros((8, 32))}) == []
        [f] = diff_batch_shapes(first, {"input_ids": np.zeros((8, 48))})
        assert f.rule == "graph/shape-varying-input"


# -------------------------------------------------------------- collectives
class TestCollectivePass:
    def _seq(self):
        return [CollectiveRecord("all_reduce", (8,), "float32", ("data",),
                                 "train.py:10"),
                CollectiveRecord("all_gather", (16,), "bfloat16", ("data",),
                                 "train.py:11"),
                CollectiveRecord("barrier", (), "-", (), "train.py:12")]

    def test_identical_sequences_clean(self):
        s = self._seq()
        assert diff_sequences({0: s, 1: s, 2: s}) == []

    def test_reorder_names_divergent_rank(self):
        s = self._seq()
        bad = [s[1], s[0], s[2]]
        [f] = diff_sequences({0: s, 1: s, 2: bad, 3: s})
        assert f.rank == 2 and f.severity == "error"
        assert "order/op mismatch" in f.message and "collective[0]" in f.citation

    def test_majority_rank_override_blames_the_pinned_minority(self):
        """The cross-rank verify pins the majority side explicitly (a
        two-way diff has no meaningful vote): with rank 0 divergent and
        rank 3 holding the majority sequence, the finding must blame
        rank 0 — not the healthy rank."""
        s = self._seq()
        bad = [s[1], s[0], s[2]]
        [f] = diff_sequences({0: bad, 3: s}, majority_rank=3)
        assert f.rank == 0
        assert "rank 0 issues" in f.message and "rank 3 (majority)" in f.message

    def test_shape_and_length_mismatch_kinds(self):
        s = self._seq()
        shp = [s[0]._replace(shape=(9,)), s[1], s[2]]
        [f] = diff_sequences([s, shp])
        assert "shape mismatch" in f.message
        [f2] = diff_sequences([s, s[:2]])
        assert "length mismatch" in f2.message

    def test_record_mode_captures_eager_collectives(self, mesh8):
        from deepspeed_tpu.comm import comm

        comm.set_mesh(mesh8)
        with record_collectives(apply_chaos=False) as rec:
            comm.all_reduce(jnp.ones((8, 4)), group="data")
            comm.barrier()
        ops = [r.op for r in rec.records]
        assert ops == ["all_reduce", "barrier"]
        assert rec.records[0].shape == (8, 4)
        assert rec.records[0].axes == ("data",)
        # recorder uninstalled after the context
        assert comm._collective_recorder is None

    def test_save_load_roundtrip(self, tmp_path):
        rec = CollectiveRecorder()
        rec.records = self._seq()
        p = str(tmp_path / "seq.json")
        rec.save(p)
        assert CollectiveRecorder.load(p) == self._seq()

    def test_fingerprint_ignores_site(self):
        a = self._seq()
        b = [r._replace(site="elsewhere.py:1") for r in a]
        assert (CollectiveRecorder().fingerprint()
                == CollectiveRecorder().fingerprint())
        ra, rb = CollectiveRecorder(), CollectiveRecorder()
        ra.records, rb.records = a, b
        assert ra.fingerprint() == rb.fingerprint()


# ------------------------------------------------------------------- chaos
@pytest.mark.chaos
class TestCollectiveMismatchChaos:
    def test_perturbation_is_deterministic_and_detected(self):
        from deepspeed_tpu.resilience.chaos import ChaosInjector

        seq = [CollectiveRecord("all_reduce", (8,), "float32", ("data",), ""),
               CollectiveRecord("all_gather", (16,), "bfloat16", ("data",), ""),
               CollectiveRecord("reduce_scatter", (32,), "float32", ("data",), "")]
        inj1 = ChaosInjector(seed=7, collective_mismatch=True)
        inj2 = ChaosInjector(seed=7, collective_mismatch=True)
        out1 = inj1.perturb_collectives(seq, rank=1)
        assert out1 == inj2.perturb_collectives(seq, rank=1)
        assert out1 != seq
        findings = diff_sequences({0: seq, 1: out1})
        assert findings and findings[0].rank == 1
        assert ("collective_record", "mismatch")[0] in inj1.log[0][0]

    def test_rank_targeting(self):
        from deepspeed_tpu.resilience.chaos import ChaosInjector

        seq = [CollectiveRecord("all_reduce", (8,), "float32", ("data",), ""),
               CollectiveRecord("barrier", (), "-", (), "")]
        inj = ChaosInjector(seed=3, collective_mismatch=True,
                            collective_mismatch_rank=5)
        assert inj.perturb_collectives(seq, rank=0) == seq
        assert inj.perturb_collectives(seq, rank=5) != seq

    def test_identical_adjacent_records_still_detected(self):
        """Swapping two records identical in the fingerprinted fields
        would be invisible to the detector — the injector must pick a
        differing pair (or mutate a shape) so every logged injection is
        provably detectable."""
        from deepspeed_tpu.resilience.chaos import ChaosInjector

        same = CollectiveRecord("all_reduce", (8,), "float32", ("data",), "")
        for seed in range(6):
            inj = ChaosInjector(seed=seed, collective_mismatch=True)
            out = inj.perturb_collectives([same, same, same], rank=0)
            assert diff_sequences({0: [same, same, same], 1: out}), seed

    def test_empty_and_single_sequences_still_diverge(self):
        from deepspeed_tpu.resilience.chaos import ChaosInjector

        inj = ChaosInjector(seed=1, collective_mismatch=True)
        assert len(inj.perturb_collectives([], rank=0)) == 1
        one = [CollectiveRecord("all_reduce", (8,), "float32", ("data",), "")]
        out = inj.perturb_collectives(one, rank=0)
        assert out[0].shape != one[0].shape

    def test_recorder_applies_installed_injector(self, mesh8):
        from deepspeed_tpu.comm import comm
        from deepspeed_tpu.resilience import chaos

        comm.set_mesh(mesh8)
        inj = chaos.ChaosInjector(seed=11, collective_mismatch=True)
        chaos.install_chaos(inj)
        try:
            with record_collectives() as rec:
                comm.all_reduce(jnp.ones((8, 2)), group="data")
                comm.all_reduce(jnp.ones((8, 4)), group="data")
            clean = CollectiveRecorder()
            with record_collectives(apply_chaos=False) as clean:
                comm.all_reduce(jnp.ones((8, 2)), group="data")
                comm.all_reduce(jnp.ones((8, 4)), group="data")
            assert rec.fingerprint() != clean.fingerprint()
            assert diff_sequences({0: clean.records, 1: rec.records})
        finally:
            chaos.uninstall_chaos()

    def test_from_env_spec(self):
        from deepspeed_tpu.resilience.chaos import ChaosInjector

        inj = ChaosInjector.from_env("seed=5,collective_mismatch=1")
        assert inj.collective_mismatch and inj.seed == 5


# ---------------------------------------------------------------- self-lint
class TestSelfLint:
    def test_repo_is_clean(self):
        """The tier-1 self-lint: untimed host collectives outside comm and
        bare time.time() in the step path cannot merge."""
        assert lint_package() == []

    def test_bare_time_in_step_path_flagged(self):
        src = "import time\n\ndef f():\n    return time.time()\n"
        [f] = lint_source(src, "runtime/engine.py")
        assert f.rule == "selflint/bare-time-in-step-path"
        assert f.citation == "runtime/engine.py:4"
        # outside the step path it is fine (e.g. a timestamp for an event)
        assert lint_source(src, "telemetry/exporters.py") == []

    def test_untimed_host_collective_flagged(self):
        src = ("from jax.experimental import multihost_utils\n"
               "def f(x):\n"
               "    return multihost_utils.process_allgather(x)\n")
        [f] = lint_source(src, "elasticity/elastic_agent.py")
        assert f.rule == "selflint/untimed-host-collective"
        # comm.py is the sanctioned routing point
        assert lint_source(src, "comm/comm.py") == []


# ----------------------------------------------------- engine + smoke matrix
def _tiny_gpt2():
    from deepspeed_tpu.models.gpt2 import GPT2Model, PRESETS

    return GPT2Model(PRESETS["gpt2-tiny"])


def _lm_batch(seq=32, batch=8):
    from deepspeed_tpu.models.gpt2 import PRESETS, synthetic_lm_batch

    return synthetic_lm_batch(batch, seq, PRESETS["gpt2-tiny"].vocab_size)


class TestEngineWiring:
    def test_strict_noop_without_block(self):
        """Without the ``analysis`` block the engine provably runs no
        analyzer code: the package is never (re)imported."""
        saved = {m: sys.modules.pop(m) for m in list(sys.modules)
                 if m.startswith("deepspeed_tpu.analysis")}
        try:
            eng, *_ = deepspeed_tpu.initialize(
                model=_tiny_gpt2(), config={**BASE_CFG,
                                            "bf16": {"enabled": True}})
            eng.train_batch(_lm_batch())
            assert not any(m.startswith("deepspeed_tpu.analysis")
                           for m in sys.modules)
            assert eng._analysis_enabled is False
        finally:
            sys.modules.update(saved)

    def test_enabled_block_runs_clean_and_fingerprints(self):
        eng, *_ = deepspeed_tpu.initialize(
            model=_tiny_gpt2(),
            config={**BASE_CFG, "bf16": {"enabled": True},
                    "analysis": {"fail_on": "error"}})
        loss = eng.train_batch(_lm_batch())
        assert np.isfinite(float(loss))
        assert eng._analysis_graph_done
        assert eng._collective_fingerprint is not None

    def test_fail_on_error_aborts_before_first_compile(self):
        class UpcastModel:
            def init_params(self, rng):
                return {"w": jax.random.normal(rng, (256, 256), jnp.float32),
                        "emb": jax.random.normal(rng, (64, 256), jnp.float32)}

            def loss(self, params, batch, rng=None):
                x = params["emb"][batch["input_ids"]]
                h = x.astype(jnp.float32) @ params["w"].astype(jnp.float32)
                return (h ** 2).mean()

        eng, *_ = deepspeed_tpu.initialize(
            model=UpcastModel(),
            config={**BASE_CFG, "bf16": {"enabled": True},
                    "analysis": {"fail_on": "error",
                                 "min_promote_elements": 1024}})
        with pytest.raises(AnalysisError, match="dtype-promotion"):
            eng.train_batch({"input_ids": np.zeros((8, 16), np.int32)})

    def test_fail_on_never_reports_only(self):
        class UpcastModel:
            def init_params(self, rng):
                return {"w": jax.random.normal(rng, (256, 256), jnp.float32)}

            def loss(self, params, batch, rng=None):
                h = batch["x"].astype(jnp.float32) @ \
                    params["w"].astype(jnp.float32)
                return (h ** 2).mean()

        eng, *_ = deepspeed_tpu.initialize(
            model=UpcastModel(),
            config={**BASE_CFG, "bf16": {"enabled": True},
                    "analysis": {"fail_on": "never",
                                 "min_promote_elements": 1024}})
        loss = eng.train_batch({"x": np.ones((8, 256), np.float32)})
        assert np.isfinite(float(loss))

    def test_scalar_batch_leaf_is_not_a_false_positive(self):
        """The engine's _shard_batch materializes every batch leaf as a
        strong-typed array, so a Python scalar riding in the batch is NOT
        a retrace hazard there — the analyzer must not flag it (the
        weak-scalar rule targets user-built steps, where the
        number-vs-array alternation bug actually lives)."""
        class ScaledModel:
            def init_params(self, rng):
                return {"w": jax.random.normal(rng, (64, 64), jnp.float32)}

            def loss(self, params, batch, rng=None):
                return ((batch["x"] @ params["w"]) * batch["scale"]).mean()

        eng, *_ = deepspeed_tpu.initialize(
            model=ScaledModel(),
            config={**BASE_CFG, "bf16": {"enabled": True},
                    "analysis": {"fail_on": "warn"}})
        loss = eng.train_batch({"x": np.ones((8, 64), np.float32),
                                "scale": 2.0})
        assert np.isfinite(float(loss))

    def test_init_fails_on_cross_field_error(self):
        with pytest.raises(AnalysisError, match="cross-field"):
            deepspeed_tpu.initialize(
                model=_tiny_gpt2(),
                config={**BASE_CFG, "bf16": {"enabled": True},
                        "zero_optimization": {
                            "stage": 1, "offload_param": {"device": "cpu"}},
                        "analysis": {"fail_on": "error"}})

    def test_shape_change_warns_but_never_aborts(self):
        eng, *_ = deepspeed_tpu.initialize(
            model=_tiny_gpt2(),
            config={**BASE_CFG, "bf16": {"enabled": True},
                    "analysis": {"fail_on": "warn"}})
        eng.train_batch(_lm_batch(seq=32))
        eng.train_batch(_lm_batch(seq=16))   # new shape: warn-once, no raise
        assert eng._analysis_batch_shapes is None


class TestSmokeMatrix:
    """Zero false-positive errors on known-good configs across the model
    family fixtures (trace-only: no engine, no compile)."""

    @pytest.mark.parametrize("family", ["gpt2", "llama", "moe"])
    @pytest.mark.parametrize("dtype_block", [{"bf16": {"enabled": True}}, {}])
    def test_family_clean(self, family, dtype_block):
        report = run_doctor({**BASE_CFG, **dtype_block}, model=family,
                            passes=("schema", "sharding", "graph"),
                            world_size=1)
        assert report.errors == [], report.render()

    def test_explicitly_requested_pass_without_inputs_says_skipped(self):
        """A pass the caller asked for by name that cannot run must say so
        (info finding), not render as a clean result. The sharding pass's
        unspecified-jit lint runs model-free (and must be CLEAN on the
        migrated tree), but its sharding-PLAN sub-pass still needs a
        fixture — the skip note says which half did not run."""
        report = run_doctor(dict(BASE_CFG), passes=("sharding", "collectives"),
                            world_size=1)
        rules = {f.rule for f in report.findings}
        assert rules == {"sharding/pass-skipped", "collectives/pass-skipped"}
        [sk] = [f for f in report.findings if f.rule == "sharding/pass-skipped"]
        assert "unspecified-jit lint ran" in sk.message
        assert all(f.severity == "info" for f in report.findings)
        assert not report.should_fail("error")

    def test_default_pass_set_skips_quietly(self):
        report = run_doctor(dict(BASE_CFG), world_size=1)
        assert report.findings == []   # header lists what ran; no noise

    def test_single_collective_log_is_not_a_clean_diff(self, tmp_path):
        rec = CollectiveRecorder()
        rec.records = [CollectiveRecord("all_reduce", (8,), "float32",
                                        ("data",), "")]
        p = str(tmp_path / "only_rank.json")
        rec.save(p)
        report = run_doctor(dict(BASE_CFG), world_size=1,
                            collective_logs=[p])
        assert [f.rule for f in report.findings] == ["collectives/pass-skipped"]

    def test_graph_skip_on_broken_config_carries_the_schema_error(self):
        report = run_doctor({**BASE_CFG, "fp16": {"enabld": True}},
                            passes=("graph",), model="gpt2", world_size=1)
        [f] = report.findings
        assert f.rule == "graph/pass-skipped"
        assert "did you mean 'enabled'" in f.message

    def test_bert_clean(self):
        report = run_doctor({**BASE_CFG, "bf16": {"enabled": True}},
                            model="bert", passes=("schema", "graph"),
                            world_size=1)
        assert report.errors == [], report.render()


# -------------------------------------------------------------------- report
class TestReport:
    def test_fail_on_semantics(self):
        r = AnalysisReport()
        r.add(Finding(rule="x/y", severity="warning", message="m"))
        assert not r.should_fail("error")
        assert r.should_fail("warn") and not r.should_fail("never")
        r.add(Finding(rule="x/z", severity="error", message="m2"))
        assert r.should_fail("error")
        with pytest.raises(AnalysisError):
            r.raise_if("error")

    def test_counted_into_telemetry(self):
        from deepspeed_tpu import telemetry
        from deepspeed_tpu.runtime.config import TelemetryConfig

        session = telemetry.TelemetrySession(
            TelemetryConfig(enabled=True, jsonl=False, prometheus=False,
                            trace=False, output_dir="/tmp/ds_doctor_t"))
        telemetry.install_session(session)
        try:
            r = AnalysisReport()
            r.add(Finding(rule="graph/dtype-promotion", severity="error",
                          message="m"))
            r.count_into_registry()
            snap = session.registry.snapshot()
            rows = [s for s in snap
                    if s["name"] == "analysis/findings"]
            assert rows and rows[0]["value"] == 1
        finally:
            telemetry.deconfigure()

    def test_render_and_json(self):
        r = AnalysisReport()
        r.extend([Finding(rule="a/b", severity="info", message="hello",
                          citation="there")], "schema")
        out = r.render()
        assert "a/b" in out and "[schema]" in out
        parsed = json.loads(r.to_json())
        assert parsed["counts"]["info"] == 1


# ---------------------------------------------------------------------- CLIs
class TestDoctorCLI:
    def _run(self, *args, cwd=None):
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "bin", "ds_doctor"), *args],
            capture_output=True, text=True, cwd=cwd, env=env, timeout=300)

    def test_acceptance_matrix(self, tmp_path):
        """The ISSUE acceptance block, end to end: typo'd sub-block key,
        bf16 graph that upcasts to fp32, and a reordered collective each
        exit non-zero naming rule + offender; all-good exits 0."""
        good = tmp_path / "good.json"
        good.write_text(json.dumps({**BASE_CFG, "bf16": {"enabled": True}}))
        typo = tmp_path / "typo.json"
        typo.write_text(json.dumps(
            {**BASE_CFG, "bf16": {"enabled": True},
             "watchdog": {"enabeld": True}}))
        upcast = tmp_path / "upcast.py"
        upcast.write_text(
            "import jax, jax.numpy as jnp\n"
            "def build_graph(cfg):\n"
            "    p = {'w': jax.ShapeDtypeStruct((512, 512), jnp.bfloat16)}\n"
            "    x = jax.ShapeDtypeStruct((64, 512), jnp.bfloat16)\n"
            "    def f(params, inp):\n"
            "        return (inp.astype(jnp.float32) @\n"
            "                params['w'].astype(jnp.float32)).sum()\n"
            "    return f, (p, x)\n")
        seq = [CollectiveRecord("all_reduce", (8,), "float32", ("data",),
                                "train.py:10"),
               CollectiveRecord("all_gather", (16,), "bfloat16", ("data",),
                                "train.py:11")]
        r0 = CollectiveRecorder(); r0.records = seq
        r0.save(str(tmp_path / "rank0.json"))
        r1 = CollectiveRecorder(); r1.records = [seq[1], seq[0]]
        r1.save(str(tmp_path / "rank1.json"))

        # 1) typo'd sub-block key -> non-zero, names rule + key
        p = self._run("--config", str(typo), "--fail-on", "error")
        assert p.returncode == 2, p.stderr
        assert "config/unknown-key" in p.stdout and "enabeld" in p.stdout \
            and "watchdog" in p.stdout

        # 2) bf16 config whose graph upcasts to fp32 -> non-zero, names op
        p = self._run("--config", str(good), "--graph", str(upcast),
                      "--passes", "schema,graph", "--fail-on", "error")
        assert p.returncode == 2, p.stderr
        assert "graph/dtype-promotion" in p.stdout and "dot_general" in p.stdout

        # 3) reordered collective -> non-zero, names the divergent rank
        p = self._run("--config", str(good), "--passes", "collectives",
                      "--collective-log", str(tmp_path / "rank0.json"),
                      "--collective-log", str(tmp_path / "rank1.json"),
                      "--fail-on", "error")
        assert p.returncode == 2, p.stderr
        assert "collectives/sequence-mismatch" in p.stdout \
            and "rank 1" in p.stdout

        # 4) all-good config + graph -> exit 0 with zero errors
        p = self._run("--config", str(good), "--model", "gpt2",
                      "--world-size", "1", "--fail-on", "error")
        assert p.returncode == 0, p.stdout + p.stderr
        assert "errors: 0" in p.stdout

    def test_ds_report_doctor_section(self, tmp_path):
        cfg = tmp_path / "c.json"
        cfg.write_text(json.dumps(
            {**BASE_CFG, "fp16": {"enabld": True}}))
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "bin", "ds_report"),
             "doctor", "--config", str(cfg), "--fail-on", "error"],
            capture_output=True, text=True, env=env, timeout=300)
        assert p.returncode == 2, p.stderr
        assert "did you mean 'enabled'" in p.stdout

    def test_selflint_pass_via_cli(self):
        p = self._run("--passes", "selflint", "--fail-on", "error")
        assert p.returncode == 0, p.stdout + p.stderr


# ------------------------------------------------------------------ comm api
class TestAllgatherHost:
    def test_single_process_shape(self):
        from deepspeed_tpu.comm import comm

        out = comm.allgather_host(np.int32(3))
        assert out.shape == (1,) and int(out[0]) == 3

    def test_recorded(self, mesh8):
        from deepspeed_tpu.comm import comm

        comm.set_mesh(mesh8)
        with record_collectives(apply_chaos=False) as rec:
            comm.allgather_host(np.zeros(4, np.float32))
        assert [r.op for r in rec.records] == ["allgather_host"]
