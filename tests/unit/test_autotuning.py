"""Autotuner tests — reference tests/unit/autotuning role: candidate space,
tuner ordering, real measured experiments, OOM/error pruning, result files."""

import json
import os

import numpy as np
import pytest

from deepspeed_tpu.autotuning import Autotuner, AutotuningConfig
from deepspeed_tpu.models.simple import SimpleModel

HIDDEN = 16


def _model_factory(remat=None):
    return SimpleModel(hidden_dim=HIDDEN, nlayers=2)


def _batch_factory(batch_size):
    rng = np.random.RandomState(0)
    return (rng.randn(batch_size, HIDDEN).astype(np.float32),
            rng.randn(batch_size, HIDDEN).astype(np.float32))


BASE = {"optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "steps_per_print": 0}


def _tuning(tmp_path, **kw):
    return AutotuningConfig(enabled=True, start_profile_step=1, end_profile_step=2,
                            results_dir=str(tmp_path / "results"),
                            exps_dir=str(tmp_path / "exps"),
                            mbs_list=[1, 2], zero_stage_list=[0, 1],
                            remat_list=["none"], **kw)


class TestAutotuner:
    def test_candidate_space(self, tmp_path):
        at = Autotuner(_model_factory, _batch_factory, BASE, _tuning(tmp_path))
        cands = at.candidate_space()
        assert len(cands) == 4  # 2 mbs x 2 stages x 1 remat
        assert all("_tune" in c for c in cands)

    def test_tune_finds_best_and_writes_results(self, tmp_path):
        at = Autotuner(_model_factory, _batch_factory, BASE, _tuning(tmp_path))
        best = at.tune()
        assert best is not None
        assert "_tuned" in best
        ok = [e for e in at.experiments if e.status == "ok"]
        assert len(ok) >= 1
        # best really is the max-metric experiment
        assert max(e.metric_val for e in ok) == \
            max(e.metric_val for e in at.experiments)
        summary = json.load(open(os.path.join(at.tuning.results_dir, "summary.json")))
        assert summary["num_experiments"] == len(at.experiments)
        assert os.path.isfile(os.path.join(at.tuning.results_dir,
                                           "ds_config_optimal.json"))

    def test_bad_candidate_is_pruned_not_fatal(self, tmp_path):
        # train_batch_size 3*8 with mbs 3: fine; mbs 5 against dp=8 divides
        # train_batch 40... make an invalid one via a bogus optimizer instead
        bad_base = {"optimizer": {"type": "NoSuchOpt", "params": {}},
                    "steps_per_print": 0}
        at = Autotuner(_model_factory, _batch_factory, bad_base,
                       _tuning(tmp_path, tuner_early_stopping=0))
        best = at.tune()
        assert best is None
        assert all(e.status in ("error", "oom") for e in at.experiments)

    def test_model_based_ordering_prefers_big_batches(self, tmp_path):
        at = Autotuner(_model_factory, _batch_factory, BASE, _tuning(tmp_path))
        ordered = at._order(at.candidate_space())
        mbs = [c["_tune"]["micro_batch"] for c in ordered]
        assert mbs[0] == max(mbs)

    def test_latency_metric(self, tmp_path):
        at = Autotuner(_model_factory, _batch_factory, BASE,
                       _tuning(tmp_path, metric="latency"))
        best = at.tune()
        assert best is not None
        ok = [e for e in at.experiments if e.status == "ok"]
        assert all(e.metric_val <= 0 for e in ok)   # latency metric = -step_time
