"""Autotuner tests — reference tests/unit/autotuning role: candidate space,
tuner ordering, real measured experiments, OOM/error pruning, result files."""

import json
import os

import numpy as np
import pytest

from deepspeed_tpu.autotuning import Autotuner, AutotuningConfig
from deepspeed_tpu.models.simple import SimpleModel

HIDDEN = 16


def _model_factory(remat=None):
    return SimpleModel(hidden_dim=HIDDEN, nlayers=2)


def _batch_factory(batch_size):
    rng = np.random.RandomState(0)
    return (rng.randn(batch_size, HIDDEN).astype(np.float32),
            rng.randn(batch_size, HIDDEN).astype(np.float32))


BASE = {"optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "steps_per_print": 0}


def _tuning(tmp_path, **kw):
    return AutotuningConfig(enabled=True, start_profile_step=1, end_profile_step=2,
                            results_dir=str(tmp_path / "results"),
                            exps_dir=str(tmp_path / "exps"),
                            mbs_list=[1, 2], zero_stage_list=[0, 1],
                            remat_list=["none"], **kw)


class TestAutotuner:
    def test_candidate_space(self, tmp_path):
        at = Autotuner(_model_factory, _batch_factory, BASE, _tuning(tmp_path))
        cands = at.candidate_space()
        assert len(cands) == 4  # 2 mbs x 2 stages x 1 remat
        assert all("_tune" in c for c in cands)

    def test_tune_finds_best_and_writes_results(self, tmp_path):
        at = Autotuner(_model_factory, _batch_factory, BASE, _tuning(tmp_path))
        best = at.tune()
        assert best is not None
        assert "_tuned" in best
        ok = [e for e in at.experiments if e.status == "ok"]
        assert len(ok) >= 1
        # best really is the max-metric experiment
        assert max(e.metric_val for e in ok) == \
            max(e.metric_val for e in at.experiments)
        summary = json.load(open(os.path.join(at.tuning.results_dir, "summary.json")))
        assert summary["num_experiments"] == len(at.experiments)
        assert os.path.isfile(os.path.join(at.tuning.results_dir,
                                           "ds_config_optimal.json"))

    def test_bad_candidate_is_pruned_not_fatal(self, tmp_path):
        # train_batch_size 3*8 with mbs 3: fine; mbs 5 against dp=8 divides
        # train_batch 40... make an invalid one via a bogus optimizer instead
        bad_base = {"optimizer": {"type": "NoSuchOpt", "params": {}},
                    "steps_per_print": 0}
        at = Autotuner(_model_factory, _batch_factory, bad_base,
                       _tuning(tmp_path, tuner_early_stopping=0))
        best = at.tune()
        assert best is None
        assert all(e.status in ("error", "oom") for e in at.experiments)

    def test_model_based_ordering_prefers_big_batches(self, tmp_path):
        at = Autotuner(_model_factory, _batch_factory, BASE, _tuning(tmp_path))
        ordered = at._order(at.candidate_space())
        mbs = [c["_tune"]["micro_batch"] for c in ordered]
        assert mbs[0] == max(mbs)

    def test_latency_metric(self, tmp_path):
        at = Autotuner(_model_factory, _batch_factory, BASE,
                       _tuning(tmp_path, metric="latency"))
        best = at.tune()
        assert best is not None
        ok = [e for e in at.experiments if e.status == "ok"]
        assert all(e.metric_val <= 0 for e in ok)   # latency metric = -step_time


class TestAutotunerAxes:
    def test_gas_tp_offload_flash_axes(self, tmp_path):
        """The widened space (reference tuner sweeps ZeRO sub-knobs too):
        gas/tp/offload/flash-block multiply the candidate set and land in the
        generated ds_configs."""
        t = _tuning(tmp_path, gas_list=[1, 2], tp_list=[1, 2],
                    offload_list=[False, True], flash_block_list=[None, 256])
        at = Autotuner(_model_factory, _batch_factory, BASE, t)
        cands = at.candidate_space()
        # 2 mbs x 2 stages x 1 remat x 2 gas x 2 tp x 2 offload x 2 fb
        assert len(cands) == 64
        got = {(c["_tune"]["gas"], c["_tune"]["tp"], c["_tune"]["offload"],
                c["_tune"]["flash_block"]) for c in cands}
        assert (2, 2, True, 256) in got
        gas2 = next(c for c in cands if c["_tune"]["gas"] == 2
                    and c["_tune"]["tp"] == 2)
        assert gas2["gradient_accumulation_steps"] == 2
        assert gas2["tpu"]["tensor"] == 2
        # tp not dividing the device count is dropped
        t2 = _tuning(tmp_path, tp_list=[1, 3])
        at2 = Autotuner(_model_factory, _batch_factory, BASE, t2)
        assert all(c["_tune"]["tp"] == 1 for c in at2.candidate_space())

    def test_hbm_cost_model_prunes_hopeless(self, tmp_path, monkeypatch):
        """A candidate whose first-order HBM estimate exceeds the budget is
        recorded as 'pruned' without compiling."""
        import dataclasses

        from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model, synthetic_lm_batch

        cfg = GPT2Config(vocab_size=256, n_positions=64, n_embd=64, n_layer=2,
                         n_head=4, use_flash_attention=False)

        def model_factory(remat="attn", flash_block=None):
            return GPT2Model(dataclasses.replace(
                cfg, remat=remat if remat != "none" else False))

        def batch_factory(bs):
            return synthetic_lm_batch(bs, 32, cfg.vocab_size)

        t = AutotuningConfig(enabled=True, start_profile_step=1,
                             end_profile_step=2,
                             results_dir=str(tmp_path / "results"),
                             exps_dir=str(tmp_path / "exps"),
                             mbs_list=[1], zero_stage_list=[0],
                             remat_list=["none"])
        at = Autotuner(model_factory, batch_factory, BASE, t, seq_len=32)
        est = at.estimate_hbm_bytes({"micro_batch": 1, "zero": 0,
                                     "remat": "none", "gas": 1, "tp": 1},
                                    n_dev=1)
        assert est is not None and est > 0
        # pretend the chip is tiny: everything prunes, nothing compiles
        class FakeDev:
            def memory_stats(self):
                return {"bytes_limit": 1024}
        import jax
        monkeypatch.setattr(jax, "local_devices", lambda: [FakeDev()])
        ran = {"n": 0}
        monkeypatch.setattr(at, "_run_one",
                            lambda exp: ran.__setitem__("n", ran["n"] + 1))
        at.tune()
        assert ran["n"] == 0
        assert all(e.status == "pruned" for e in at.experiments)

    def test_model_based_order_prefers_inhbm_over_offload(self, tmp_path):
        t = _tuning(tmp_path, offload_list=[True, False])
        at = Autotuner(_model_factory, _batch_factory, BASE, t)
        ordered = at._order(at.candidate_space())
        first_off = next(i for i, c in enumerate(ordered)
                         if c["_tune"]["offload"])
        assert all(not c["_tune"]["offload"] for c in ordered[:first_off])


class TestDsTuneCLI:
    def test_family_dispatch_bert(self, tmp_path, capsys, monkeypatch):
        """ds_tune drives non-GPT2 families (reference autotuning runner
        role): bert preset + MLM batches through a real 2-candidate tune."""
        import runpy
        import sys

        monkeypatch.setattr(sys, "argv", [
            "ds_tune", "--model", "bert-tiny", "--seq", "64",
            "--mbs", "2", "--remat", "none", "--steps", "1",
            "--output", str(tmp_path)])
        runpy.run_path(os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))), "bin", "ds_tune"),
            run_name="__main__")
        out = capsys.readouterr().out.strip().splitlines()[-1]
        res = json.loads(out)
        assert res["status"] == "ok"
        assert res["tuned"]["micro_batch"] == 2


def test_heads_axis_reaches_factory(tmp_path):
    """The r5 fat-head axis: heads_list expands the space and the winning
    candidate's n_head reaches the model factory (and the reported config)."""
    import jax

    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model

    seen = []

    def factory(remat="none", n_head=None):
        cfg = GPT2Config(vocab_size=128, n_positions=32, n_embd=16,
                         n_layer=1, n_head=n_head or 2, remat=False,
                         use_flash_attention=False)
        seen.append(cfg.n_head)
        return GPT2Model(cfg)

    def batches(bs):
        rng = np.random.RandomState(0)
        return {"input_ids": rng.randint(0, 128, size=(bs, 16)).astype(np.int32)}

    t = AutotuningConfig(enabled=True, start_profile_step=1, end_profile_step=2,
                         results_dir=str(tmp_path / "r"),
                         exps_dir=str(tmp_path / "e"),
                         mbs_list=[1], zero_stage_list=[0],
                         remat_list=["none"], heads_list=[2, 4],
                         tuner_type="gridsearch")
    at = Autotuner(factory, batches,
                   {"optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "steps_per_print": 0}, t)
    cands = at.candidate_space()
    assert {c["_tune"]["n_head"] for c in cands} == {2, 4}
    best = at.tune()
    assert best is not None and best["_tuned"]["n_head"] in (2, 4)
    assert set(seen) >= {2, 4}
