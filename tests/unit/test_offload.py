"""ZeRO-Offload tests: native aio, NVMe tensor swapping, swapped optimizer,
engine NVMe stepping (reference tests/unit/ops/aio/test_aio.py +
runtime/zero offload suites)."""

import os

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.simple import SimpleModel


def _aio_or_skip():
    from deepspeed_tpu.ops.aio import aio_available

    if not aio_available():
        pytest.skip("async_io C++ build unavailable")


class TestAio:
    def test_sync_roundtrip(self, tmp_path):
        _aio_or_skip()
        from deepspeed_tpu.ops.aio import AsyncIOHandle

        h = AsyncIOHandle(block_size=4096, thread_count=4)
        data = np.random.default_rng(0).bytes(100_000)
        src = np.frombuffer(data, dtype=np.uint8).copy()
        path = str(tmp_path / "blob.bin")
        h.sync_pwrite(src, path)
        assert AsyncIOHandle.file_size(path) == src.nbytes
        dst = np.zeros_like(src)
        h.sync_pread(dst, path)
        np.testing.assert_array_equal(src, dst)

    def test_async_many(self, tmp_path):
        _aio_or_skip()
        from deepspeed_tpu.ops.aio import AsyncIOHandle

        h = AsyncIOHandle(block_size=1 << 14, thread_count=8)
        arrays = [np.random.default_rng(i).integers(0, 255, size=50_000).astype(np.uint8)
                  for i in range(8)]
        for i, a in enumerate(arrays):
            h.async_pwrite(a, str(tmp_path / f"f{i}.bin"))
        h.wait()
        outs = [np.zeros_like(a) for a in arrays]
        for i, o in enumerate(outs):
            h.async_pread(o, str(tmp_path / f"f{i}.bin"))
        h.wait()
        for a, o in zip(arrays, outs):
            np.testing.assert_array_equal(a, o)

    def test_read_missing_raises(self, tmp_path):
        _aio_or_skip()
        from deepspeed_tpu.ops.aio import AsyncIOHandle

        h = AsyncIOHandle()
        with pytest.raises(IOError):
            h.async_pread(np.zeros(16, np.uint8), str(tmp_path / "nope.bin"))

    def test_perf_sweep_recommends_config(self, tmp_path):
        """aio bench sweep (reference csrc/aio/py_test/
        aio_bench_perf_sweep.py:348 role): measures every point, verifies
        data integrity, and recommends a ds_config 'aio' block."""
        _aio_or_skip()
        from deepspeed_tpu.autotuning.aio_sweep import sweep_and_save

        out = str(tmp_path / "sweep.json")
        res = sweep_and_save(str(tmp_path / "nvme"), output_json=out,
                             file_mb=1, block_sizes=(1 << 16, 1 << 20),
                             thread_counts=(2, 4), repeats=1)
        assert res is not None
        assert len(res["results"]) == 4
        rec = res["recommended_aio"]
        assert rec["block_size"] in (1 << 16, 1 << 20)
        assert rec["thread_count"] in (2, 4)
        assert all(r["read_gbps"] > 0 and r["write_gbps"] > 0
                   for r in res["results"])
        import json as _json
        with open(out) as f:
            assert _json.load(f)["recommended_aio"] == rec


class TestSwapper:
    def test_roundtrip_and_stats(self, tmp_path):
        _aio_or_skip()
        from deepspeed_tpu.runtime.swap_tensor import AsyncTensorSwapper

        sw = AsyncTensorSwapper(str(tmp_path))
        t1 = np.random.default_rng(1).normal(size=(64, 32)).astype(np.float32)
        t2 = np.random.default_rng(2).normal(size=(100,)).astype(np.float16)
        sw.swap_out("layer1/w", t1)
        sw.swap_out("layer2.b", t2)
        sw.synchronize()
        sw.release("layer1/w")
        sw.release("layer2.b")
        assert sw.stats()["resident_buffers"] == 0

        sw.swap_in("layer1/w")
        sw.swap_in("layer2.b")
        np.testing.assert_array_equal(sw.retrieve("layer1/w"), t1)
        np.testing.assert_array_equal(sw.retrieve("layer2.b"), t2)

    def test_unknown_name(self, tmp_path):
        _aio_or_skip()
        from deepspeed_tpu.runtime.swap_tensor import AsyncTensorSwapper

        sw = AsyncTensorSwapper(str(tmp_path))
        with pytest.raises(KeyError):
            sw.swap_in("ghost")


class TestSwappedOptimizer:
    def test_matches_optax_adamw(self, tmp_path):
        """Disk-swapped Adam must track optax.adamw step for step."""
        _aio_or_skip()
        import jax
        import jax.numpy as jnp
        import optax

        from deepspeed_tpu.runtime.swap_tensor.optimizer_swapper import SwappedOptimizer

        rng = np.random.default_rng(0)
        params = {"a": rng.normal(size=(32, 16)).astype(np.float32),
                  "b": rng.normal(size=(16,)).astype(np.float32),
                  "c": rng.normal(size=(8, 8)).astype(np.float32)}
        hp = dict(lr=1e-2, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.01)

        swopt = SwappedOptimizer(str(tmp_path), "adamw", hp, buffer_count=2)
        swopt.init_from_params(params)

        ref_opt = optax.adamw(hp["lr"], b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.01)
        ref_params = {k: jnp.asarray(v) for k, v in params.items()}
        ref_state = ref_opt.init(ref_params)

        cur = params
        for step in range(3):
            grads = {k: rng.normal(size=v.shape).astype(np.float32)
                     for k, v in params.items()}
            cur = swopt.step(grads)
            updates, ref_state = ref_opt.update({k: jnp.asarray(g) for k, g in grads.items()},
                                                ref_state, ref_params)
            ref_params = optax.apply_updates(ref_params, updates)
        for k in params:
            np.testing.assert_allclose(cur[k], np.asarray(ref_params[k]),
                                       rtol=1e-5, atol=1e-6)


class TestEngineOffload:
    def test_cpu_offload_config_accepted_on_cpu_backend(self):
        """CPU backend has one memory space — offload downgrades with a log,
        training still works (the TPU path is exercised in hardware verify)."""
        model = SimpleModel(hidden_dim=16, nlayers=2)
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
            "train_batch_size": 8,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 2,
                                  "offload_optimizer": {"device": "cpu"}},
            "steps_per_print": 0})
        assert not engine._host_offload_opt
        rng = np.random.default_rng(0)
        batch = (rng.normal(size=(8, 16)).astype(np.float32),
                 rng.normal(size=(8, 16)).astype(np.float32))
        l0 = float(engine.train_batch(batch))
        for _ in range(4):
            ln = float(engine.train_batch(batch))
        assert ln < l0

    def test_nvme_offload_numerics_under_dp_mesh(self, tmp_path):
        """Offloaded Adam must match the in-HBM optimizer bit-for-bit-ish on
        a multi-device mesh: ZeRO-2 dp=8 grads are device-sharded, the NVMe
        path pulls/updates/pushes per leaf — the composition the VERDICT
        called out as untested (offload numerics under a sharded mesh)."""
        _aio_or_skip()
        from deepspeed_tpu.comm import comm
        from deepspeed_tpu.parallel.topology import build_mesh

        def train(offload: bool):
            comm.cdb = None
            mesh = build_mesh(axis_dims={"pipe": 1, "data": 8, "expert": 1,
                                         "seq": 1, "tensor": 1})
            comm.init_distributed(mesh=mesh, verbose=False)
            zero = {"stage": 2}
            if offload:
                zero["offload_optimizer"] = {"device": "nvme",
                                             "nvme_path": str(tmp_path / "swap"),
                                             "buffer_count": 2}
            engine, *_ = deepspeed_tpu.initialize(
                model=SimpleModel(hidden_dim=16, nlayers=2),
                config={"train_batch_size": 8,
                        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
                        "zero_optimization": zero,
                        "steps_per_print": 0})
            rng = np.random.default_rng(0)
            batch = (rng.normal(size=(8, 16)).astype(np.float32),
                     rng.normal(size=(8, 16)).astype(np.float32))
            losses = [float(engine.train_batch(batch)) for _ in range(4)]
            import jax

            flat = {"/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                             for p in path): np.asarray(leaf)
                    for path, leaf in jax.tree_util.tree_flatten_with_path(
                        engine.state.params)[0]}
            return losses, flat

        losses_ref, params_ref = train(offload=False)
        losses_off, params_off = train(offload=True)
        np.testing.assert_allclose(losses_off, losses_ref, rtol=1e-4)
        assert params_ref.keys() == params_off.keys()
        for k in params_ref:
            np.testing.assert_allclose(params_off[k], params_ref[k],
                                       rtol=1e-4, atol=1e-5, err_msg=k)

    def test_nvme_offload_end_to_end(self, tmp_path):
        """Full ZeRO-Infinity-style loop: grads on device, Adam on host with
        NVMe-swapped state; loss falls and optimizer state lives on disk."""
        _aio_or_skip()
        model = SimpleModel(hidden_dim=16, nlayers=2)
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
            "train_batch_size": 8,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 2,
                                  "offload_optimizer": {"device": "nvme",
                                                        "nvme_path": str(tmp_path),
                                                        "buffer_count": 2}},
            "steps_per_print": 0})
        assert engine._nvme_optimizer is not None
        rng = np.random.default_rng(0)
        batch = (rng.normal(size=(8, 16)).astype(np.float32),
                 rng.normal(size=(8, 16)).astype(np.float32))
        losses = [float(engine.train_batch(batch)) for _ in range(5)]
        assert losses[-1] < losses[0], losses
        assert engine._nvme_optimizer.state_bytes() > 0
        swp_files = [f for f in os.listdir(tmp_path) if f.endswith(".swp")]
        # 3 files (master + 2 moments) per parameter tensor
        assert len(swp_files) >= 3


class TestStreamedChunkedAdam:
    def test_streamed_chunked_matches_inhbm(self, monkeypatch):
        """The leaf-streamed + CHUNKED Adam (the ZeRO-Offload big-model path
        that lets gpt2-1.3b/xl step on a 16G chip) must match the in-HBM
        optimizer. CPU backends have one memory space, so offload placement
        is forced post-init — what this pins is the chunk slicing / DUS
        bookkeeping and the ordering-token chain, which are
        placement-independent."""
        import jax
        import jax.numpy as jnp

        from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model, synthetic_lm_batch

        cfg = GPT2Config(vocab_size=256, n_positions=32, n_embd=32, n_layer=4,
                         n_head=4, use_flash_attention=False)
        batch = synthetic_lm_batch(8, 16, cfg.vocab_size, seed=11)
        ds = {"train_batch_size": 8,
              "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
              "bf16": {"enabled": True}, "steps_per_print": 0}

        def losses(streamed):
            from deepspeed_tpu.comm import comm

            comm.cdb = None
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=GPT2Model(cfg), config=dict(ds))
            if streamed:
                # ~4KB chunks → every stacked leaf takes the n_chunks>1 path
                monkeypatch.setenv("DS_TPU_OFFLOAD_CHUNK_BYTES", str(4 * 1024))
                engine._host_offload_opt = True
                engine._offload_streamed_cached = True
            return [float(engine.train_batch(batch)) for _ in range(4)]

        base = losses(False)
        chunked = losses(True)
        np.testing.assert_allclose(base, chunked, rtol=2e-3, atol=2e-4)


class TestZeroInfinityParams:
    def test_layerwise_nvme_matches_inhbm(self, tmp_path):
        """ZeRO-Infinity param offload (params + Adam state on NVMe,
        layerwise step) must match the in-HBM engine numerically (reference
        partitioned_param_swapper.py + stage3 remote_device='nvme' role)."""
        import jax
        import jax.numpy as jnp

        from deepspeed_tpu.comm import comm
        from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model, synthetic_lm_batch
        from deepspeed_tpu.runtime.zero.infinity import ZeroInfinityEngine

        cfg = GPT2Config(vocab_size=256, n_positions=32, n_embd=32, n_layer=4,
                         n_head=4, dtype=jnp.float32, remat=False,
                         use_flash_attention=False)
        ds = {"train_batch_size": 8,
              "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
              "zero_optimization": {
                  "stage": 3,
                  "offload_param": {"device": "nvme",
                                    "nvme_path": str(tmp_path / "p")}},
              "steps_per_print": 0}
        batch = synthetic_lm_batch(8, 16, cfg.vocab_size, seed=2)

        comm.cdb = None
        zengine, _, _, _ = deepspeed_tpu.initialize(model=GPT2Model(cfg),
                                                    config=ds)
        assert isinstance(zengine, ZeroInfinityEngine)
        linf = [float(zengine.train_batch(batch)) for _ in range(4)]

        comm.cdb = None
        base_ds = {k: v for k, v in ds.items() if k != "zero_optimization"}
        engine, _, _, _ = deepspeed_tpu.initialize(model=GPT2Model(cfg),
                                                   config=base_ds)
        lbase = [float(engine.train_batch(batch)) for _ in range(4)]
        np.testing.assert_allclose(lbase, linf, rtol=2e-4, atol=2e-5)

        # export round trip: the gathered tree runs the plain model
        params = zengine.gather_params()
        import jax.numpy as jnp2
        logits = GPT2Model(cfg).apply(
            {k: (jnp2.asarray(v) if not isinstance(v, dict) else
                 {kk: jnp2.asarray(vv) for kk, vv in v.items()})
             for k, v in params.items()},
            jnp2.asarray(batch["input_ids"][:, :8]))
        assert np.isfinite(np.asarray(logits)).all()

        # checkpoint round trip: snapshot NVMe state, drift, restore, verify
        zengine.save_checkpoint(str(tmp_path / "ck"), tag="t")
        shared_before = {n: np.asarray(v) for n, v in zengine.shared.items()}
        drift = float(zengine.train_batch(batch))
        zengine.load_checkpoint(str(tmp_path / "ck"), tag="t")
        assert zengine.global_steps == 4
        for n, v in zengine.shared.items():
            np.testing.assert_array_equal(np.asarray(v), shared_before[n])
        resumed = float(zengine.train_batch(batch))
        np.testing.assert_allclose(resumed, drift, rtol=1e-5)


class TestStreamOverlapKnob:
    """stream_overlap precedence: config field wins; DS_TPU_OFFLOAD_OVERLAP
    env is the fallback only while the field is None / the block absent."""

    def test_config_wins_over_env(self, monkeypatch):
        from deepspeed_tpu.runtime.engine import _resolve_stream_overlap
        from deepspeed_tpu.runtime.zero.config import \
            DeepSpeedZeroOffloadOptimizerConfig as Off

        monkeypatch.setenv("DS_TPU_OFFLOAD_OVERLAP", "1")
        assert _resolve_stream_overlap(Off(device="cpu", stream_overlap=False)) is False
        monkeypatch.setenv("DS_TPU_OFFLOAD_OVERLAP", "0")
        assert _resolve_stream_overlap(Off(device="cpu", stream_overlap=True)) is True

    def test_env_fallback_when_unset(self, monkeypatch):
        from deepspeed_tpu.runtime.engine import _resolve_stream_overlap
        from deepspeed_tpu.runtime.zero.config import \
            DeepSpeedZeroOffloadOptimizerConfig as Off

        monkeypatch.delenv("DS_TPU_OFFLOAD_OVERLAP", raising=False)
        assert _resolve_stream_overlap(Off(device="cpu")) is False
        assert _resolve_stream_overlap(None) is False
        monkeypatch.setenv("DS_TPU_OFFLOAD_OVERLAP", "1")
        assert _resolve_stream_overlap(Off(device="cpu")) is True
        assert _resolve_stream_overlap(None) is True

    def test_ds_config_parses_stream_overlap(self):
        from deepspeed_tpu.runtime.config import DeepSpeedConfig

        cfg = DeepSpeedConfig({
            "train_batch_size": 8,
            "zero_optimization": {
                "stage": 1,
                "offload_optimizer": {"device": "cpu", "stream_overlap": True}}})
        assert cfg.zero_config.offload_optimizer.stream_overlap is True

    def test_autotuner_candidates_carry_stream_overlap(self):
        # the winning ds_config the tuner reports must reproduce the result
        # without env knobs (review finding r4)
        from deepspeed_tpu.autotuning.autotuner import (Autotuner,
                                                        AutotuningConfig)

        t = AutotuningConfig(enabled=True, mbs_list=[1], gas_list=[1],
                             zero_stage_list=[1], remat_list=[False],
                             offload_list=[True], offload_overlap_list=[True, False])
        tuner = Autotuner.__new__(Autotuner)
        tuner.tuning = t
        tuner.base_config = {"optimizer": {"type": "AdamW", "params": {}}}
        cands = tuner.candidate_space()
        offs = [c["zero_optimization"]["offload_optimizer"] for c in cands]
        assert {o["stream_overlap"] for o in offs} == {True, False}
