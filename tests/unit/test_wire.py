"""ds_wire tests (runtime/wire.py + the ``wire`` ds_config block): the
quantizer's padded-group accounting, quantize/dequant roundtrip bounds,
qgZ hierarchical-vs-flat numerics and error-feedback convergence, the
strict no-op + byte-identical-HLO contract, THE 8-dev static_comm_bytes
on/off acceptance (inter-host all-gather + reduce-scatter ≥3× lower at
``wire: full`` with losses within the pinned tolerance), ds_xray zero
findings on the rewritten programs, quantized collective-fingerprint
stability, the chaos ``collective`` drill on the quantized serial gather,
and the perf-ledger ``wire_mode`` identity."""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model, synthetic_lm_batch

# the ACCEPTANCE fixture: weight-dominated gpt2 (params >> activations, so
# the ZeRO-3 weight gathers are the comm story, as they are at real scale)
ACFG = GPT2Config(vocab_size=128, n_positions=8, n_embd=256, n_layer=2,
                  n_head=2, remat=False, use_flash_attention=False)
AB, AT = 8, 8

# the micro fixture for cheap engine drills
MCFG = GPT2Config(vocab_size=128, n_positions=16, n_embd=64, n_layer=2,
                  n_head=2, remat=False, use_flash_attention=False)
MB, MT = 8, 16


def wire_config(model_cfg=ACFG, bs=AB, *, wire=None, tpu=None, overlap=None,
                **over):
    cfg = {
        "train_batch_size": bs,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 3,
                              "stage3_param_persistence_threshold": 0},
        "steps_per_print": 0,
    }
    if overlap is not None:
        cfg["overlap"] = overlap
    if tpu is not None:
        cfg["tpu"] = tpu
    if wire is not None:
        cfg["wire"] = wire
    cfg.update(over)
    return cfg


def make_engine(cfg, model_cfg=ACFG):
    engine, _, _, _ = deepspeed_tpu.initialize(model=GPT2Model(model_cfg),
                                               config=cfg)
    return engine


WIRE_FULL = {"weight_quant_bits": 8, "secondary_partition": True,
             "secondary_size": 4, "grad_quant_bits": 4}
TPU_2x4 = {"data": 2, "ici": 4}


# ---------------------------------------------------------------------------
# ops/quantizer.py — padded-group accounting (the satellite fix, pinned)
# ---------------------------------------------------------------------------
@pytest.mark.wire
class TestQuantizerPadding:
    def test_group_layout_pads_instead_of_collapsing(self):
        from deepspeed_tpu.ops.quantizer import quant_group_layout

        assert quant_group_layout(100, 64) == (64, 2, 128)
        assert quant_group_layout(128, 64) == (64, 2, 128)
        assert quant_group_layout(37, 16) == (16, 3, 48)
        # group >= dim: one whole-dim group, nothing padded
        assert quant_group_layout(48, 64) == (48, 1, 48)
        assert quant_group_layout(48, 0) == (48, 1, 48)

    def test_nbytes_bills_padded_wire_bytes(self):
        """static_comm_bytes bills what actually crosses the wire: the
        PADDED codes (+ scales), not the logical element count."""
        from deepspeed_tpu.ops.quantizer import quantize_tensor

        w = jnp.asarray(np.random.RandomState(0).randn(100, 8),
                        jnp.float32)
        qt = quantize_tensor(w, num_bits=8, group_size=64)
        assert qt.q.shape == (2, 64, 8)          # 2 groups of 64, padded
        assert qt.scale.shape == (2, 8)
        assert qt.nbytes == 2 * 64 * 8 + 2 * 8 * 4
        assert qt.nbytes > 100 * 8               # > logical int8 bytes

    @pytest.mark.parametrize("shape,gs", [((100, 8), 64), ((37,), 16),
                                          ((3, 100, 8), 32)])
    def test_roundtrip_exact_shape_and_bounded_error(self, shape, gs):
        from deepspeed_tpu.ops.quantizer import (dequantize_tensor,
                                                 quantize_tensor)

        w = jnp.asarray(np.random.RandomState(1).randn(*shape), jnp.float32)
        qt = quantize_tensor(w, num_bits=8, group_size=gs)
        back = dequantize_tensor(qt)
        assert back.shape == w.shape
        # per-group symmetric int8: |err| <= group absmax / 127 / 2 + round
        bound = float(jnp.max(jnp.abs(w))) / 127.0 * 0.51 * 2
        assert float(jnp.max(jnp.abs(back - w))) <= max(bound, 2e-2)

    def test_int4_roundtrip_padded(self):
        from deepspeed_tpu.ops.quantizer import (dequantize_tensor,
                                                 quantize_tensor)

        w = jnp.asarray(np.random.RandomState(2).randn(100, 4), jnp.float32)
        qt = quantize_tensor(w, num_bits=4, group_size=64)
        assert qt.q.shape == (2, 32, 4)          # nibble-packed, padded
        back = dequantize_tensor(qt)
        assert back.shape == w.shape
        assert float(jnp.max(jnp.abs(back - w))) <= \
            float(jnp.max(jnp.abs(w))) / 7.0 * 0.51 * 2 + 1e-3


# ---------------------------------------------------------------------------
# spec surgery
# ---------------------------------------------------------------------------
@pytest.mark.wire
class TestSpecSurgery:
    def _mesh(self):
        return Mesh(np.asarray(jax.devices()).reshape(1, 2, 1, 4, 1, 1, 1),
                    ("pipe", "data", "mics", "ici", "expert", "seq",
                     "tensor"))

    def test_secondary_spec_swaps_dp_for_ici(self):
        from deepspeed_tpu.runtime.wire import secondary_spec

        sp = secondary_spec(P(None, ("data", "ici")), 2, ("data", "ici"))
        assert tuple(sp) == (None, "ici")
        sp = secondary_spec(P("tensor", ("data", "ici")), 2, ("data", "ici"))
        assert tuple(sp) == ("tensor", "ici")
        # no dp on the leaf: unchanged
        sp = secondary_spec(P(None, "tensor"), 2, ("data", "ici"))
        assert tuple(sp) == (None, "tensor")

    def test_plan_leaf_wire_maps_out_dim_sharding(self):
        from deepspeed_tpu.runtime.wire import plan_leaf_wire

        mesh = self._mesh()
        lw = plan_leaf_wire(mesh, (64, 256), P(None, ("data", "ici")),
                            ("data", "ici"), bits=8, group_size=64,
                            secondary=True)
        assert lw is not None
        assert lw.gs == 64 and lw.view_shape == (64, 256)
        assert tuple(lw.s_q.spec) == (None, None, ("data", "ici"))
        assert tuple(lw.g_q.spec) == (None, None, None)
        assert tuple(lw.sec_q.spec) == (None, None, None, "ici")  # stacked
        # codes + scales wire bytes: 64*256 int8 + 1*256 f32 scales
        assert lw.wire_nbytes == 64 * 256 + 256 * 4

    def test_plan_leaf_wire_skips_unmappable(self):
        from deepspeed_tpu.runtime.wire import plan_leaf_wire

        mesh = self._mesh()
        # 1-D bias sharded on its only dim: G=2 not divisible by dp world 8
        assert plan_leaf_wire(mesh, (128,), P(("data", "ici"),),
                              ("data", "ici"), bits=8, group_size=64,
                              secondary=False) is None
        # int4 needs an even group
        assert plan_leaf_wire(mesh, (33, 256), P(None, ("data", "ici")),
                              ("data", "ici"), bits=4, group_size=33,
                              secondary=False) is None


# ---------------------------------------------------------------------------
# qgZ — hierarchical quantized exchange numerics (pure, shard_map)
# ---------------------------------------------------------------------------
def _qgz_mesh():
    return Mesh(np.asarray(jax.devices()).reshape(2, 4), ("data", "ici"))


@pytest.mark.wire
class TestQGZNumerics:
    def test_hierarchical_matches_flat_and_exact_mean(self):
        from deepspeed_tpu.runtime.wire import (
            hierarchical_quantized_allreduce, qgz_state_shapes)
        from deepspeed_tpu.utils import shard_map_compat

        mesh = _qgz_mesh()
        n, W = 1000, 8
        rng = np.random.RandomState(0)
        xs = jnp.asarray(rng.randn(W, n), jnp.float32)
        exact = np.asarray(jnp.mean(xs, axis=0))

        def run(inner):
            wl, sl = qgz_state_shapes(n, 4 if inner else 1,
                                      2 if inner else 8)
            we = jnp.zeros((W, wl), jnp.float32)
            se = jnp.zeros((W, sl), jnp.float32)

            def k(x, we, se):
                out, nwe, nse = hierarchical_quantized_allreduce(
                    x[0], we[0], se[0],
                    outer_axis="data" if inner else ("data", "ici"),
                    inner_axis="ici" if inner else None, bits=8,
                    group_size=64)
                return out[None], nwe[None], nse[None]

            fn = shard_map_compat(
                k, mesh=mesh,
                in_specs=(P(("data", "ici")), P(("data", "ici")),
                          P(("data", "ici"))),
                out_specs=(P(("data", "ici")), P(("data", "ici")),
                           P(("data", "ici"))),
                check_vma=False)
            out, _, _ = fn(xs, we, se)
            return np.asarray(out)

        hier = run(inner=True)
        flat = run(inner=False)
        # every device agrees, and both schemes track the exact mean with
        # bounded quantization error (two quantization hops)
        scale = np.abs(exact).max() + 1.0
        for out in (hier, flat):
            assert np.allclose(out, out[0:1], atol=1e-6)
            assert np.max(np.abs(out[0] - exact)) < 0.1 * scale

    def test_error_feedback_residuals_compensate(self):
        """int4 with persistent residuals: the time-averaged reconstruction
        converges to the true mean (the error-feedback contract the 1-bit
        family relies on), while a residual-free int4 reconstruction keeps
        its bias."""
        from deepspeed_tpu.runtime.wire import (
            hierarchical_quantized_allreduce, qgz_state_shapes)
        from deepspeed_tpu.utils import shard_map_compat

        mesh = _qgz_mesh()
        n, W, steps = 256, 8, 24
        rng = np.random.RandomState(3)
        xs = jnp.asarray(rng.randn(W, n), jnp.float32)
        exact = np.asarray(jnp.mean(xs, axis=0))
        wl, sl = qgz_state_shapes(n, 4, 2)

        def k(x, we, se):
            out, nwe, nse = hierarchical_quantized_allreduce(
                x[0], we[0], se[0], outer_axis="data", inner_axis="ici",
                bits=4, group_size=64)
            return out[None], nwe[None], nse[None]

        fn = shard_map_compat(
            k, mesh=mesh,
            in_specs=(P(("data", "ici")),) * 3,
            out_specs=(P(("data", "ici")),) * 3, check_vma=False)
        fn = jax.jit(fn)
        we = jnp.zeros((W, wl), jnp.float32)
        se = jnp.zeros((W, sl), jnp.float32)
        acc = np.zeros(n)
        for _ in range(steps):
            out, we, se = fn(xs, we, se)
            acc += np.asarray(out)[0]
        err_avg = np.abs(acc / steps - exact).max()
        one_shot, *_ = fn(xs, jnp.zeros_like(we), jnp.zeros_like(se))
        err_one = np.abs(np.asarray(one_shot)[0] - exact).max()
        assert err_avg < 0.5 * max(err_one, 1e-9) or err_avg < 5e-3


# ---------------------------------------------------------------------------
# config surface + schema cross-fields
# ---------------------------------------------------------------------------
@pytest.mark.wire
class TestWireConfigSurface:
    def test_unknown_key_rejected_with_hint(self):
        from deepspeed_tpu.runtime.config import DeepSpeedConfig

        with pytest.raises(ValueError, match="weight_quant_bits"):
            DeepSpeedConfig(wire_config_dict({"weight_quant_bit": 8}))

    def test_bad_bits_rejected(self):
        from deepspeed_tpu.runtime.config import DeepSpeedConfig

        with pytest.raises(ValueError, match="4 or 8"):
            DeepSpeedConfig(wire_config_dict({"weight_quant_bits": 6}))

    def test_cross_fields(self):
        from deepspeed_tpu.analysis.schema import walk_config

        # wire below ZeRO-3: warning (nothing to shrink)
        findings, _ = walk_config(
            wire_config_dict({}, stage=1, overlap=True), world_size=8)
        assert any(f.rule == "config/cross-field" and f.severity == "warning"
                   and "stage" in f.citation for f in findings)
        # wire without overlap: warning (the gather rides the overlap scan)
        findings, _ = walk_config(
            wire_config_dict({}, overlap=False), world_size=8)
        assert any("wire vs overlap" == f.citation for f in findings)
        # grad quant + 1-bit optimizer: error (both own the exchange)
        cfg = wire_config_dict({"grad_quant_bits": 8}, stage=0, overlap=True)
        cfg["optimizer"] = {"type": "OneBitAdam", "params": {"lr": 1e-3}}
        findings, _ = walk_config(cfg, world_size=8)
        assert any(f.severity == "error" and
                   "wire.grad_quant_bits vs optimizer.type" == f.citation
                   for f in findings)
        # hpZ with no explicit host factoring: INFO, not an error
        findings, _ = walk_config(
            wire_config_dict({"secondary_partition": True}, overlap=True),
            world_size=8)
        hits = [f for f in findings
                if f.citation == "wire.secondary_partition vs tpu.ici"]
        assert hits and all(f.severity == "info" for f in hits)

    def test_ledger_compare_flags_wire_mode_change(self):
        from deepspeed_tpu.perf.cli import _world_tag
        from deepspeed_tpu.perf.ledger import compare

        old = {"metric": "m (x)", "value": 1.0, "wire_mode": "off",
               "world_size": 8, "mesh_axes": "data=2×ici=4"}
        new = dict(old, wire_mode="qwz+hpz")
        r = compare(old, new)
        assert r["world_changed"] and r["fingerprint_changed"]
        assert "wire changed off -> qwz+hpz" in _world_tag(r)
        # entries predating the key read as "off" (no spurious flag)
        r2 = compare({"metric": "m (x)", "value": 1.0},
                     dict(old, wire_mode="off"))
        assert not r2["world_changed"]


def wire_config_dict(wire, stage=3, overlap=False):
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage},
        "wire": dict(wire),
        "steps_per_print": 0,
    }
    if overlap:
        cfg["overlap"] = {}
    return cfg


# ---------------------------------------------------------------------------
# strict no-op + byte-identical HLO
# ---------------------------------------------------------------------------
@pytest.mark.wire
class TestStrictNoOp:
    def test_block_absent_never_imports_module(self):
        mods = [m for m in list(sys.modules)
                if m == "deepspeed_tpu.runtime.wire"]
        saved = {m: sys.modules.pop(m) for m in mods}
        try:
            engine = make_engine(wire_config(MCFG, MB, overlap={}), MCFG)
            engine.train_batch(synthetic_lm_batch(MB, MT, MCFG.vocab_size))
            assert engine._wire is None
            assert "deepspeed_tpu.runtime.wire" not in sys.modules
        finally:
            sys.modules.update(saved)

    def test_block_absent_step_is_byte_identical(self):
        """An engine without the block and one with ``enabled: false``
        lower the EXACT same step program — the wire rewrites leave zero
        residue when off."""
        def lowered(engine):
            abstract = lambda tree: jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                               sharding=x.sharding), tree)
            batch = engine._shard_batch(
                synthetic_lm_batch(MB, MT, MCFG.vocab_size))
            with engine.mesh:
                return engine._get_compiled_train_batch(1).lower(
                    abstract(engine.state), abstract(batch)).as_text()

        t_absent = lowered(make_engine(wire_config(MCFG, MB, overlap={}),
                                       MCFG))
        t_disabled = lowered(make_engine(
            wire_config(MCFG, MB, overlap={},
                        wire={"enabled": False, "weight_quant_bits": 8}),
            MCFG))
        assert t_absent == t_disabled


# ---------------------------------------------------------------------------
# THE acceptance: ≥3× lower inter-host AG+RS wire bytes, losses pinned
# ---------------------------------------------------------------------------
def _acceptance_engine(wire, ledger=None, tmp_path=None, name=""):
    cfg = wire_config(ACFG, AB, wire=wire, tpu=dict(TPU_2x4),
                      overlap={"grad_reduce": "post"})
    if ledger is not None:
        cfg["telemetry"] = {"enabled": True,
                            "output_dir": str(tmp_path / f"tel_{name}"),
                            "prometheus": False, "flush_interval": 1_000_000}
        cfg["perf"] = {"ledger_path": str(ledger)}
    engine = make_engine(cfg, ACFG)
    batch = synthetic_lm_batch(AB, AT, ACFG.vocab_size, seed=0)
    losses = [float(engine.train_batch(batch)) for _ in range(3)]
    from deepspeed_tpu.analysis.xray import static_comm_for_engine

    sc = static_comm_for_engine(engine)
    entry = None
    if ledger is not None:
        entry = engine.perf_record(f"wire-drill ({name})", 1.0, "MFU",
                                   config={"wire": name}, timed_steps=2)
    return engine, losses, sc, entry


@pytest.mark.wire
@pytest.mark.perf
class TestStaticCommAcceptance:
    def test_full_vs_off_inter_gather_scatter_3x(self, tmp_path):
        from deepspeed_tpu.analysis.xray import inter_host_bytes, run_xray
        from deepspeed_tpu.perf.cli import main as perf_main

        ledger = tmp_path / "led.jsonl"
        e0, l0, sc0, ent0 = _acceptance_engine(None, ledger, tmp_path, "off")
        e1, l1, sc1, ent1 = _acceptance_engine(WIRE_FULL, ledger, tmp_path,
                                               "full")
        # --- the acceptance number: inter-host all-gather + reduce-scatter
        inter0 = inter_host_bytes(sc0["by_kind"])
        inter1 = inter_host_bytes(sc1["by_kind"])
        assert inter0 == sc0["inter_gather_scatter_bytes"]
        assert inter1 >= 1  # the quantized build gather still crosses hosts
        assert inter0 / inter1 >= 3.0, (inter0, inter1)
        # total static comm improves too (the gate's headline metric)
        assert sc1["static_comm_bytes"] < sc0["static_comm_bytes"]
        # --- losses within the pinned tolerance of the fp-exact step
        assert max(abs(a - b) for a, b in zip(l0, l1)) < 0.02
        # --- exposed comm no worse than the overlapped baseline (both are
        # fused overlapped programs: nothing exposed on the host timeline)
        exp0 = (ent0["attribution"] or {}).get("exposed_comm_us_per_step", 0)
        exp1 = (ent1["attribution"] or {}).get("exposed_comm_us_per_step", 0)
        assert exp1 <= exp0 + 1.0
        # --- the ledger pair carries the identity + the gate enforces it
        assert ent0["wire_mode"] == "off"
        assert ent1["wire_mode"] == "qwz+hpz+qgz"
        assert ent0["mesh_axes"] == ent1["mesh_axes"]
        base = tmp_path / "off.jsonl"
        cand = tmp_path / "full.jsonl"
        base.write_text(json.dumps(ent0) + "\n")
        cand.write_text(json.dumps(ent1) + "\n")
        assert perf_main(["gate", "--baseline", str(base),
                          "--candidate", str(cand),
                          "--metric", "static_comm_bytes"]) == 0
        assert perf_main(["gate", "--baseline", str(cand),
                          "--candidate", str(base),
                          "--metric", "static_comm_bytes"]) == 2
        # --- ds_xray collective-order + promise-vs-actual: zero findings
        # on the rewritten (quantized) program
        result = run_xray(plan=e1.plan)
        errors = [f for f in result.findings if f.severity == "error"]
        assert not errors, [str(f) for f in errors]

    def test_qwz_quantized_gather_fingerprints_stable(self):
        """PR 4 collective fingerprints hash the quantized op identity
        stably: same config ⇒ same fingerprint, and it differs from the
        full-width schedule's."""
        fps = []
        for _ in range(2):
            cfg = wire_config(MCFG, MB, wire={"weight_quant_bits": 8},
                              tpu=dict(TPU_2x4), overlap={},
                              analysis={"fail_on": "error"})
            e = make_engine(cfg, MCFG)
            e.train_batch(synthetic_lm_batch(MB, MT, MCFG.vocab_size))
            assert e._collective_fingerprint is not None
            fps.append(e._collective_fingerprint)
        assert fps[0] == fps[1]
        cfg = wire_config(MCFG, MB, tpu=dict(TPU_2x4), overlap={},
                          analysis={"fail_on": "error"})
        e = make_engine(cfg, MCFG)
        e.train_batch(synthetic_lm_batch(MB, MT, MCFG.vocab_size))
        assert e._collective_fingerprint != fps[0]


# ---------------------------------------------------------------------------
# chaos `collective` drill on the quantized serial gather
# ---------------------------------------------------------------------------
@pytest.mark.wire
@pytest.mark.chaos
def test_chaos_delay_inflates_quantized_serial_gather(tmp_path):
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.resilience import chaos as chaos_mod

    cfg = wire_config(MCFG, MB, wire={"weight_quant_bits": 8},
                      tpu=dict(TPU_2x4),
                      overlap={"schedule": "serial"},
                      telemetry={"enabled": True,
                                 "output_dir": str(tmp_path / "t"),
                                 "prometheus": False,
                                 "flush_interval": 1_000_000})
    engine = make_engine(cfg, MCFG)
    batch = synthetic_lm_batch(MB, MT, MCFG.vocab_size)
    inj = chaos_mod.ChaosInjector(delay_at={"collective": [3]},
                                  max_delay_s=0.5)
    chaos_mod.install_chaos(inj)
    try:
        for _ in range(3):
            engine.train_batch(batch)
        spans = [e for e in telemetry.get_session().tracer.events
                 if e.get("cat") == "comm"]
        assert len(spans) == 3
        # the quantized gather phase carries its (smaller) wire bytes and
        # the injected delay inflates the SAME timed span
        from deepspeed_tpu.ops.quantizer import quantized_nbytes  # noqa

        dense = sum(int(np.prod(l.shape)) * 2
                    for l in jax.tree.leaves(engine.state.params))
        assert 0 < spans[0]["args"]["bytes"] < dense
        assert spans[2]["dur"] - spans[1]["dur"] >= 0.3 * 1e6
        assert any(op == "collective" and "delay" in act
                   for op, act, _ in inj.log)
    finally:
        chaos_mod.uninstall_chaos()
        telemetry.deconfigure()


# ---------------------------------------------------------------------------
# qgZ engine path — stage-0 shard-mapped step with residuals in opt state
# ---------------------------------------------------------------------------
@pytest.mark.wire
class TestQGZEngine:
    def test_qgz_grad_sync_trains(self):
        cfg = wire_config(
            MCFG, MB, wire={"grad_quant_bits": 8, "weight_quant_bits": 0},
            tpu=dict(TPU_2x4),
            zero_optimization={"stage": 0})
        engine = make_engine(cfg, MCFG)
        from deepspeed_tpu.runtime.wire import QGZAdam

        assert isinstance(engine.optimizer, QGZAdam)
        assert engine._onebit        # rides the shard-mapped step protocol
        batch = synthetic_lm_batch(MB, MT, MCFG.vocab_size, seed=0)
        losses = [float(engine.train_batch(batch)) for _ in range(4)]
        assert losses[-1] < losses[0]
        # the error-feedback residuals ride the optimizer state,
        # per-worker (leading world dim), and become nonzero once the
        # quantizer has clipped something
        st = engine.state.opt_state
        we = jax.tree.leaves(st.worker_error)
        assert all(w.shape[0] == 8 for w in we)
        assert any(float(jnp.max(jnp.abs(w))) > 0 for w in we)

    def test_qgz_with_onebit_refused(self):
        cfg = wire_config(MCFG, MB, wire={"grad_quant_bits": 8},
                          zero_optimization={"stage": 0})
        cfg["optimizer"] = {"type": "OneBitAdam", "params": {"lr": 1e-3}}
        with pytest.raises(ValueError, match="1-bit"):
            make_engine(cfg, MCFG)

    def test_qgz_inert_at_stage3(self):
        cfg = wire_config(MCFG, MB,
                          wire={"grad_quant_bits": 8,
                                "weight_quant_bits": 0},
                          overlap={})
        engine = make_engine(cfg, MCFG)
        from deepspeed_tpu.runtime.wire import QGZAdam

        assert not isinstance(engine.optimizer, QGZAdam)
        assert not engine._onebit


# ---------------------------------------------------------------------------
# bench --wire e2e (the satellite's smoke ledger line)
# ---------------------------------------------------------------------------
@pytest.mark.wire
@pytest.mark.perf
def test_bench_smoke_devices_wire(tmp_path):
    """`bench.py --smoke --devices 8 --wire full` runs gpt2-tiny as a real
    simulated 8-dev ZeRO-3 job on the ici-factored mesh; the ledger entry
    stamps wire_mode + the host-split static comm."""
    import subprocess

    ledger = tmp_path / "led.jsonl"
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("BENCH_")}
    env.pop("XLA_FLAGS", None)
    env["BENCH_TELEMETRY_DIR"] = str(tmp_path / "tel")
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--smoke",
         "--devices", "8", "--wire", "full", "--ledger", str(ledger)],
        capture_output=True, text=True, timeout=600, env=env, cwd=repo)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = json.loads([l for l in proc.stdout.splitlines()
                       if l.startswith("{")][-1])
    assert line["config"]["n_dev"] == 8
    assert line["config"]["wire"] == "full"
    assert "wire=full" in line["metric"]
    assert line["wire_mode"] == "qwz+hpz+qgz"
    assert line["mesh_axes"] == "data=2×ici=4"
    att = line.get("attribution") or {}
    by_kind = (att.get("static_comm") or {}).get("by_kind") or {}
    assert any(k.endswith("/intra") for k in by_kind)
