"""ds_race — host-side concurrency analysis tests.

Three layers under test, mirroring deepspeed_tpu/analysis/race.py:

* the STATIC pass — lock-graph extraction over fixture trees (the seeded
  ABBA is the reverted PR-7 frontend/breaker deadlock, and it must fire
  with BOTH call sites named), the fixed shared-RLock shape staying
  clean, blocking-under-lock, signal-handler safety, and the
  ``# race-allow`` suppression contract (a suppression without a
  justification is itself a finding);
* the RUNTIME witness — the instrumented lock factory records per-thread
  acquisition order, and the offline pass flags an inversion two threads
  exercised in sequence (no deadlock ever manifested — that is the
  point);
* the LIFECYCLE registry — spawn_thread/leaked_threads, the
  disowned-by-design exemption, and the lock-holders table the SIGUSR1
  stack dump carries.

Plus the wiring pins: the repo itself lints to ZERO race findings
(tier-1), the config knobs round-trip the schema pass with did-you-mean
and cross-field checks, and ``bin/ds_doctor race`` exits 2 on findings.
"""

import json
import os
import subprocess
import sys
import textwrap
import threading

import pytest

from deepspeed_tpu.analysis.lockgraph import Aliases, LockGraph
from deepspeed_tpu.analysis.race import (RULE_ALLOW, RULE_BLOCKING,
                                         RULE_ORDER, RULE_SIGNAL,
                                         RULE_WITNESS, lint_race,
                                         load_witness, witness_findings)
from deepspeed_tpu.utils import locks as _locks

pytestmark = pytest.mark.race

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _write(root, name, src):
    path = os.path.join(str(root), name)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(textwrap.dedent(src))
    return path


# --------------------------------------------------------------- lockgraph
class TestLockGraph:
    def test_aliases_union_find_and_reentrancy(self):
        al = Aliases()
        al.mark_reentrant("b")
        al.union("a", "b")
        assert al.find("a") == al.find("b") == "a"  # lexicographic canon
        # reentrancy propagates through the union, in both directions
        assert al.is_reentrant("a") and al.is_reentrant("b")
        al.union("c", "a")
        assert al.is_reentrant("c")

    def test_two_node_cycle_cites_both_edges(self):
        g = LockGraph()
        g.add_edge("A", "B", "x.py:10", "x.py:11")
        g.add_edge("B", "A", "y.py:20", "y.py:21")
        cycles = g.cycles()
        assert len(cycles) == 1
        edges = {(s, d) for s, d, _, _ in cycles[0]}
        assert edges == {("A", "B"), ("B", "A")}
        sites = {site for e in cycles[0] for site in e[2:]}
        assert {"x.py:11", "y.py:21"} <= sites

    def test_self_loop_is_a_single_edge_cycle(self):
        g = LockGraph()
        g.add_edge("L", "L", "m.py:5", "m.py:9")
        assert g.cycles() == [[("L", "L", "m.py:5", "m.py:9")]]

    def test_dag_has_no_cycles_and_first_citation_wins(self):
        g = LockGraph()
        g.add_edge("A", "B", "a.py:1", "a.py:2")
        g.add_edge("A", "B", "b.py:7", "b.py:8")   # later sighting
        g.add_edge("B", "C", "a.py:3", "a.py:4")
        assert g.cycles() == []
        assert g.edges[("A", "B")] == ("a.py:1", "a.py:2", 2)


# ------------------------------------------------------------- static pass
ABBA_BREAKER = """
    import threading


    class CircuitBreaker:
        def __init__(self, on_transition=None):
            self._lock = threading.RLock()
            self._on_transition = on_transition

        def admits(self):
            with self._lock:
                return True

        def record_failure(self):
            with self._lock:
                if self._on_transition is not None:
                    self._on_transition()
"""

ABBA_FRONTEND = """
    import threading

    from breaker import CircuitBreaker


    class Front:
        def __init__(self):
            self._lock = threading.RLock()
            self.breaker = CircuitBreaker(on_transition=self._on_breaker)

        def submit(self):
            with self._lock:
                return self.breaker.admits()

        def _on_breaker(self):
            with self._lock:
                pass
"""


class TestStaticPass:
    def test_seeded_abba_fires_with_both_sites(self, tmp_path):
        """The reverted PR-7 deadlock: submit holds the frontend lock and
        enters the breaker; the breaker's transition callback re-enters
        the frontend lock. Two locks, both orders — the static pass must
        name BOTH acquire sites without ever running the code."""
        _write(tmp_path, "breaker.py", ABBA_BREAKER)
        _write(tmp_path, "frontend.py", ABBA_FRONTEND)
        findings = lint_race(root=str(tmp_path))
        order = [f for f in findings if f.rule == RULE_ORDER]
        assert len(order) == 1, [f.message for f in findings]
        msg = order[0].message
        assert "frontend.py" in msg and "breaker.py" in msg

    def test_fixed_shared_lock_shape_is_clean(self, tmp_path):
        """The actual PR-7 fix — ONE shared RLock injected into the
        breaker — must read as one reentrant order class, not a cycle."""
        _write(tmp_path, "breaker.py", """
            import threading


            class CircuitBreaker:
                def __init__(self, on_transition=None, lock=None):
                    self._lock = lock if lock is not None else threading.RLock()
                    self._on_transition = on_transition

                def admits(self):
                    with self._lock:
                        return True

                def record_failure(self):
                    with self._lock:
                        if self._on_transition is not None:
                            self._on_transition()
        """)
        _write(tmp_path, "frontend.py", """
            import threading

            from breaker import CircuitBreaker


            class Front:
                def __init__(self):
                    rlock = threading.RLock()
                    self._lock = threading.Condition(rlock)
                    self.breaker = CircuitBreaker(
                        on_transition=self._on_breaker, lock=rlock)

                def submit(self):
                    with self._lock:
                        return self.breaker.admits()

                def _on_breaker(self):
                    with self._lock:
                        pass
        """)
        assert lint_race(root=str(tmp_path)) == []

    def test_blocking_under_lock_and_allow_contract(self, tmp_path):
        _write(tmp_path, "a.py", """
            import threading
            import time


            class Snap:
                def __init__(self):
                    self._lock = threading.Lock()

                def bad(self):
                    with self._lock:
                        time.sleep(1.0)

                def allowed(self):
                    with self._lock:
                        # race-allow: blocking-under-lock — test fixture
                        time.sleep(1.0)
        """)
        findings = lint_race(root=str(tmp_path))
        blocking = [f for f in findings if f.rule == RULE_BLOCKING]
        assert len(blocking) == 1
        assert "time.sleep" in blocking[0].message
        assert "a.py:12" in blocking[0].citation

    def test_allow_without_justification_is_a_finding(self, tmp_path):
        _write(tmp_path, "a.py", """
            import threading
            import time

            _L = threading.Lock()


            def f():
                with _L:
                    # race-allow: blocking-under-lock
                    time.sleep(1.0)
        """)
        findings = lint_race(root=str(tmp_path))
        assert any(f.rule == RULE_ALLOW and "no justification" in f.message
                   for f in findings)
        # the unjustified comment does NOT suppress
        assert any(f.rule == RULE_BLOCKING for f in findings)

    def test_allow_with_unknown_rule_is_a_finding(self, tmp_path):
        _write(tmp_path, "a.py", """
            # race-allow: not-a-rule — whatever
            X = 1
        """)
        findings = lint_race(root=str(tmp_path))
        assert any(f.rule == RULE_ALLOW and "unknown rule" in f.message
                   for f in findings)

    def test_signal_handler_rules(self, tmp_path):
        _write(tmp_path, "handlers.py", """
            import signal
            import threading

            from deepspeed_tpu.utils import locks

            _flag = False
            _L = threading.Lock()


            def _drain():
                pass


            @locks.signal_safe("flag flip only; test fixture")
            def _safe_drain():
                pass


            def install_bad():
                def _h(signum, frame):
                    _drain()
                signal.signal(signal.SIGTERM, _h)


            def install_locking():
                def _h(signum, frame):
                    with _L:
                        pass
                signal.signal(signal.SIGTERM, _h)


            def install_good():
                def _h(signum, frame):
                    global _flag
                    _flag = True
                    _safe_drain()
                signal.signal(signal.SIGTERM, _h)
        """)
        findings = lint_race(root=str(tmp_path))
        sig = [f for f in findings if f.rule == RULE_SIGNAL]
        msgs = "\n".join(f.message for f in sig)
        assert any("_drain" in f.message and "install_bad" not in f.citation
                   for f in sig)
        assert "acquires lock" in msgs
        # the flag + @signal_safe handler produced nothing
        assert not any("_safe_drain" in m for m in msgs.splitlines())

    def test_signal_safe_without_justification_is_a_finding(self, tmp_path):
        _write(tmp_path, "a.py", """
            from deepspeed_tpu.utils import locks


            @locks.signal_safe("")
            def f():
                pass
        """)
        findings = lint_race(root=str(tmp_path))
        assert any(f.rule == RULE_ALLOW and "signal_safe" in f.message
                   for f in findings)

    def test_allowlist_suppresses_and_flags_unknown(self, tmp_path):
        _write(tmp_path, "breaker.py", ABBA_BREAKER)
        _write(tmp_path, "frontend.py", ABBA_FRONTEND)
        out = lint_race(root=str(tmp_path),
                        allowlist=("race/lock-order:frontend.py",))
        assert not any(f.rule == RULE_ORDER for f in out)
        out2 = lint_race(root=str(tmp_path),
                         allowlist=("race/not-a-rule",))
        assert any(f.rule == RULE_ALLOW and "unknown rule" in f.message
                   for f in out2)

    def test_repo_tree_has_zero_findings(self):
        """THE tier-1 assert: the framework's own lock discipline is
        clean — every deliberate exception carries a verified in-code
        justification. A refactor that introduces a lock-order cycle, a
        blocking call under a framework lock, or an unsafe signal handler
        fails HERE, with both call sites named, before it ships."""
        assert lint_race() == []


# ---------------------------------------------------------- runtime witness
class TestWitness:
    def setup_method(self):
        _locks.enable_witness(reset=True)

    def teardown_method(self):
        _locks.disable_witness()
        _locks.reset_witness()

    def test_abba_inversion_caught_without_deadlock(self):
        """Two threads exercise A->B and B->A in SEQUENCE (events make the
        schedule deterministic — nothing ever deadlocks), yet the unioned
        order graph holds both edges and the offline pass names both
        acquire sites."""
        a = _locks.make_lock("test.wit.a")
        b = _locks.make_lock("test.wit.b")
        first_done = threading.Event()

        def t1():
            with a:
                with b:
                    pass
            first_done.set()

        def t2():
            first_done.wait(5.0)
            with b:
                with a:
                    pass

        th1 = threading.Thread(target=t1)
        th2 = threading.Thread(target=t2)
        th1.start(); th2.start()
        th1.join(5.0); th2.join(5.0)
        findings = witness_findings()
        wit = [f for f in findings if f.rule == RULE_WITNESS]
        assert len(wit) == 1
        msg = wit[0].message
        assert "test.wit.a" in msg and "test.wit.b" in msg
        assert "test_race.py" in msg      # the acquire sites are cited

    def test_consistent_order_is_clean(self):
        a = _locks.make_lock("test.wit.c")
        b = _locks.make_lock("test.wit.d")
        for _ in range(3):
            with a:
                with b:
                    pass
        assert witness_findings() == []

    def test_reentrant_self_nesting_is_not_an_inversion(self):
        r = _locks.make_rlock("test.wit.r")
        with r:
            with r:
                pass
        assert witness_findings() == []

    def test_condition_shares_its_rlock_order_class(self):
        """The serving-frontend shape: a Condition over an injected
        witness rlock is the SAME order class — wait/notify nesting under
        the shared lock must not read as two locks."""
        rlock = _locks.make_rlock("test.wit.front")
        cond = _locks.make_condition("test.wit.front", rlock)
        with cond:
            with rlock:
                pass
        assert witness_findings() == []

    def test_save_load_roundtrip(self, tmp_path):
        a = _locks.make_lock("test.wit.s1")
        b = _locks.make_lock("test.wit.s2")
        with a:
            with b:
                pass
        path = str(tmp_path / "wit.json")
        _locks.save_witness(path)
        edges = load_witness(path)
        assert any(e["src"] == "test.wit.s1" and e["dst"] == "test.wit.s2"
                   for e in edges)
        # a second rank observing the reverse order: union -> inversion
        edges.append({"src": "test.wit.s2", "dst": "test.wit.s1",
                      "count": 1, "src_site": "other_rank.py:1",
                      "dst_site": "other_rank.py:2"})
        wit = witness_findings(edges)
        assert len(wit) == 1 and wit[0].rule == RULE_WITNESS

    def test_witness_off_records_nothing(self):
        _locks.disable_witness()
        _locks.reset_witness()
        a = _locks.make_lock("test.wit.off1")
        b = _locks.make_lock("test.wit.off2")
        with a:
            with b:
                pass
        assert _locks.witness_edges() == []


# -------------------------------------------------------- thread lifecycle
class TestThreadLifecycle:
    def test_spawned_thread_is_registered_and_joins_clean(self):
        done = threading.Event()
        t = _locks.spawn_thread(done.wait, name="ds-test-worker",
                                owner="test", args=(5.0,))
        t.start()
        assert any(r.name == "ds-test-worker" and r.owner == "test"
                   for r in _locks.live_framework_threads())
        done.set()
        assert _locks.leaked_threads(timeout=5.0, owner="test") == []

    def test_leak_sentinel_names_the_survivor(self):
        stop = threading.Event()
        t = _locks.spawn_thread(stop.wait, name="ds-test-leaker",
                                owner="test", args=(30.0,))
        t.start()
        try:
            leaked = _locks.leaked_threads(timeout=0.05, owner="test")
            assert [r.name for r in leaked] == ["ds-test-leaker"]
        finally:
            stop.set()
            t.join(5.0)

    def test_disowned_by_design_is_exempt(self):
        stop = threading.Event()
        t = _locks.spawn_thread(stop.wait, name="ds-test-disowned",
                                owner="test", expect_join=False, args=(30.0,))
        t.start()
        try:
            assert _locks.leaked_threads(timeout=0.05, owner="test") == []
        finally:
            stop.set()
            t.join(5.0)

    def test_lock_holders_table_in_stack_dump(self, tmp_path):
        """The watchdog SIGUSR1 dump gains the current-lock-holders table:
        'which thread holds what, acquired where' is exactly the question
        a wedged-fleet stack dump exists to answer."""
        from deepspeed_tpu.resilience.watchdog import dump_all_stacks

        lk = _locks.make_lock("test.holders")
        path = str(tmp_path / "dump.txt")
        with lk:
            holders = _locks.current_lock_holders()
            assert any(h["lock"] == "test.holders" for h in holders)
            dump_all_stacks(path, reason="test")
        with open(path) as f:
            text = f.read()
        assert "test.holders" in text
        assert threading.current_thread().name in text


# --------------------------------------------------------- config + schema
class TestConfigKnobs:
    def test_race_pass_is_known_and_default(self):
        from deepspeed_tpu.analysis.doctor import (ALL_PASSES,
                                                   DEFAULT_PASSES,
                                                   ENGINE_PASSES)
        from deepspeed_tpu.runtime.config import AnalysisConfig

        assert "race" in ALL_PASSES
        assert "race" in DEFAULT_PASSES
        assert "race" in ENGINE_PASSES
        assert AnalysisConfig(passes=["race"]).passes == ["race"]
        with pytest.raises(ValueError, match="unknown pass"):
            AnalysisConfig(passes=["rage"])

    def test_knob_typo_gets_did_you_mean(self):
        from deepspeed_tpu.analysis.schema import walk_config

        findings, _ = walk_config(
            {"train_batch_size": 8, "analysis": {"race_witnes": True}},
            world_size=8)
        msg = "\n".join(f.message for f in findings)
        assert "race_witnes" in msg and "race_witness" in msg

    def test_witness_without_telemetry_cross_field(self):
        from deepspeed_tpu.analysis.schema import walk_config

        findings, cfg = walk_config(
            {"train_batch_size": 8, "analysis": {"race_witness": True}},
            world_size=8)
        assert cfg is not None and cfg.analysis.race_witness
        assert any(f.rule == "config/cross-field"
                   and "race_witness" in f.message for f in findings)

    def test_allowlist_unknown_rule_cross_field(self):
        from deepspeed_tpu.analysis.schema import walk_config

        findings, _ = walk_config(
            {"train_batch_size": 8,
             "analysis": {"race_allowlist": ["race/bogus:thing"]}},
            world_size=8)
        assert any(f.rule == "config/cross-field"
                   and "race/bogus" in f.message for f in findings)

    def test_run_doctor_race_pass(self):
        from deepspeed_tpu.analysis.doctor import run_doctor

        rep = run_doctor({"train_batch_size": 8}, passes=("race",),
                         world_size=8)
        assert [f for f in rep.findings if f.pass_name == "race"] == []


# ------------------------------------------------------------------- CLI
class TestCLI:
    def _doctor(self, *args):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "bin", "ds_doctor"), *args],
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})

    def test_race_needs_no_config_and_repo_is_clean(self):
        proc = self._doctor("race")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "race" in proc.stdout

    def test_seeded_abba_exits_2_naming_both_sites(self, tmp_path):
        _write(tmp_path, "breaker.py", ABBA_BREAKER)
        _write(tmp_path, "frontend.py", ABBA_FRONTEND)
        proc = self._doctor("race", "--root", str(tmp_path))
        assert proc.returncode == 2, proc.stdout + proc.stderr
        assert "frontend.py" in proc.stdout and "breaker.py" in proc.stdout
        # ...and --allow suppresses it back to a clean exit
        proc2 = self._doctor("race", "--root", str(tmp_path),
                             "--allow", "race/lock-order")
        assert proc2.returncode == 0, proc2.stdout + proc2.stderr

    def test_witness_file_inversion_exits_2(self, tmp_path):
        path = str(tmp_path / "wit.json")
        with open(path, "w") as f:
            json.dump({"version": 1, "edges": [
                {"src": "A", "dst": "B", "count": 1,
                 "src_site": "x.py:1", "dst_site": "x.py:2"},
                {"src": "B", "dst": "A", "count": 1,
                 "src_site": "y.py:3", "dst_site": "y.py:4"},
            ]}, f)
        proc = self._doctor("race", "--witness", path)
        assert proc.returncode == 2, proc.stdout + proc.stderr
        assert "witness" in proc.stdout
        assert "x.py:2" in proc.stdout and "y.py:4" in proc.stdout

    def test_json_output(self):
        proc = self._doctor("race", "--json")
        assert proc.returncode == 0
        payload = json.loads(proc.stdout)
        assert payload["counts"]["error"] == 0

    def test_race_passes_flag_without_config(self):
        proc = self._doctor("--passes", "race")
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_ds_report_race_section(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bin", "ds_report"), "race"],
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "race" in proc.stdout
