"""Data pipeline tests — reference tests/unit/runtime/test_data_efficiency
role: curriculum schedules, seqlen application during training, random-LTD
scheduler math + gather/scatter ops."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
from deepspeed_tpu.runtime.data_pipeline import (CurriculumScheduler,
                                                 RandomLTDScheduler,
                                                 apply_seqlen_curriculum,
                                                 random_ltd_gather,
                                                 random_ltd_scatter)
from deepspeed_tpu.runtime.data_pipeline.data_routing import random_ltd_sample


class TestCurriculumScheduler:
    def test_fixed_linear(self):
        s = CurriculumScheduler({"curriculum_type": "seqlen",
                                 "min_difficulty": 8, "max_difficulty": 64,
                                 "schedule_type": "fixed_linear",
                                 "schedule_config": {"total_curriculum_step": 100,
                                                     "difficulty_step": 8}})
        assert s.get_difficulty(0) == 8
        assert s.get_difficulty(100) == 64
        mid = s.get_difficulty(50)
        assert 8 < mid < 64 and mid % 8 == 0
        # monotone
        vals = [s.get_difficulty(t) for t in range(0, 120, 10)]
        assert vals == sorted(vals)

    def test_fixed_root(self):
        s = CurriculumScheduler({"min_difficulty": 8, "max_difficulty": 64,
                                 "schedule_type": "fixed_root",
                                 "schedule_config": {"total_curriculum_step": 100,
                                                     "difficulty_step": 8,
                                                     "root_degree": 2}})
        # sqrt schedule front-loads difficulty vs linear
        lin = CurriculumScheduler({"min_difficulty": 8, "max_difficulty": 64,
                                   "schedule_type": "fixed_linear",
                                   "schedule_config": {"total_curriculum_step": 100,
                                                       "difficulty_step": 8}})
        assert s.get_difficulty(25) >= lin.get_difficulty(25)
        assert s.get_difficulty(200) == 64

    def test_fixed_discrete(self):
        s = CurriculumScheduler({"min_difficulty": 2, "max_difficulty": 6,
                                 "schedule_type": "fixed_discrete",
                                 "schedule_config": {"difficulty": [2, 4, 6],
                                                     "max_step": [5, 10]}})
        assert s.get_difficulty(3) == 2
        assert s.get_difficulty(7) == 4
        assert s.get_difficulty(50) == 6

    def test_custom(self):
        s = CurriculumScheduler({"min_difficulty": 1, "max_difficulty": 10,
                                 "schedule_type": "custom"})
        s.set_custom_get_difficulty(lambda t: min(10, 1 + t))
        assert s.get_difficulty(3) == 4

    def test_state_roundtrip(self):
        s = CurriculumScheduler({"min_difficulty": 8, "max_difficulty": 64,
                                 "schedule_type": "fixed_linear",
                                 "schedule_config": {"total_curriculum_step": 100,
                                                     "difficulty_step": 8}})
        s.update_difficulty(50)
        sd = s.state_dict()
        s2 = CurriculumScheduler({"min_difficulty": 8, "max_difficulty": 64,
                                  "schedule_type": "fixed_linear",
                                  "schedule_config": {"total_curriculum_step": 100,
                                                      "difficulty_step": 8}})
        s2.load_state_dict(sd)
        assert s2.get_current_difficulty() == s.get_current_difficulty()


class TestApplySeqlen:
    def test_dict_batch(self):
        b = {"input_ids": np.zeros((4, 32), np.int32),
             "labels": np.zeros((4, 32), np.int32),
             "meta": np.zeros((4,))}
        out = apply_seqlen_curriculum(b, 16)
        assert out["input_ids"].shape == (4, 16)
        assert out["labels"].shape == (4, 16)
        assert out["meta"].shape == (4,)

    def test_engine_applies_curriculum(self):
        cfg = GPT2Config(vocab_size=256, n_positions=64, n_embd=32, n_layer=2,
                         n_head=2, remat=False, use_flash_attention=False)
        engine, *_ = deepspeed_tpu.initialize(
            model=GPT2Model(cfg),
            config={"train_batch_size": 8,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "curriculum_learning": {
                        "enabled": True, "curriculum_type": "seqlen",
                        "min_difficulty": 8, "max_difficulty": 32,
                        "schedule_type": "fixed_linear",
                        "schedule_config": {"total_curriculum_step": 4,
                                            "difficulty_step": 8}},
                    "steps_per_print": 0})
        assert engine.curriculum_learning_enabled()
        rng = np.random.RandomState(0)
        batch = {"input_ids": rng.randint(0, 256, size=(8, 32)).astype(np.int32)}
        difficulties = []
        for _ in range(5):
            loss = float(engine.train_batch(batch))
            difficulties.append(engine.curriculum_scheduler.get_current_difficulty())
        assert np.isfinite(loss)
        assert difficulties[0] == 8
        assert difficulties[-1] == 32
        assert difficulties == sorted(difficulties)


class TestRandomLTD:
    def _sched(self):
        return RandomLTDScheduler({
            "total_layer_num": 12, "random_ltd_layer_num": 8,
            "global_batch_size": 4,
            "schedule": {"min_value": 16, "max_value": 64,
                         "schedule_type": "fixed_linear",
                         "schedule_config": {"require_steps": 10,
                                             "seq_per_step": 16}}})

    def test_schedule_ramp(self):
        s = self._sched()
        assert s.get_value(0) == 16
        assert s.get_value(10) == 64
        assert s.update_seq(5) in range(16, 65, 16)
        assert s.consumed_layer_tokens > 0

    def test_token_accounting(self):
        s = self._sched()
        total = s.get_total_layer_tokens(3)
        # per step: B * (kept*ltd_layers + full*other_layers)
        assert total > 0

    def test_gather_scatter_roundtrip(self):
        rng = jax.random.PRNGKey(0)
        x = jnp.asarray(np.random.RandomState(0).randn(2, 16, 4).astype(np.float32))
        idx = random_ltd_sample(rng, 16, 8, 2)
        assert idx.shape == (2, 8)
        small = random_ltd_gather(x, idx)
        assert small.shape == (2, 8, 4)
        # scatter the gathered tokens back -> identical where kept
        back = random_ltd_scatter(small * 2.0, idx, x)
        picked = np.take_along_axis(np.asarray(back), np.asarray(idx)[..., None], axis=1)
        np.testing.assert_allclose(picked, np.asarray(small) * 2.0)

    def test_state_roundtrip(self):
        s = self._sched()
        s.update_seq(5)
        sd = s.state_dict()
        s2 = self._sched()
        s2.load_state_dict(sd)
        assert s2.get_current_seq() == s.get_current_seq()


class TestRandomLTDIntegration:
    def test_random_ltd_training_loop(self):
        """End-to-end random-LTD pattern (reference basic_layer
        RandomLayerTokenDrop role): middle 'layers' of a toy net train on a
        scheduled token subset; kept-count ramps and the loss still falls."""
        import optax

        sched = RandomLTDScheduler({
            "total_layer_num": 4, "random_ltd_layer_num": 2,
            "global_batch_size": 4,
            "schedule": {"min_value": 8, "max_value": 16,
                         "schedule_type": "fixed_linear",
                         "schedule_config": {"require_steps": 6,
                                             "seq_per_step": 8}}})
        D, T, B = 8, 16, 4
        key = jax.random.PRNGKey(0)
        params = {"w_in": jax.random.normal(key, (D, D)) * 0.3,
                  "w_mid": jax.random.normal(jax.random.fold_in(key, 1), (D, D)) * 0.3,
                  "w_out": jax.random.normal(jax.random.fold_in(key, 2), (D, D)) * 0.3}

        def loss_fn(params, x, y, kept, rng):
            h = jnp.tanh(x @ params["w_in"])
            # random-LTD "middle layer": process only `kept` tokens, scatter back
            idx = random_ltd_sample(rng, T, kept, B)
            small = random_ltd_gather(h, idx)
            small = jnp.tanh(small @ params["w_mid"])
            h = random_ltd_scatter(small, idx, h)
            out = h @ params["w_out"]
            return jnp.mean((out - y) ** 2)

        opt = optax.adam(1e-2)
        opt_state = opt.init(params)
        rng_np = np.random.RandomState(0)
        x = jnp.asarray(rng_np.randn(B, T, D).astype(np.float32))
        y = jnp.asarray(rng_np.randn(B, T, D).astype(np.float32))

        from functools import partial

        @partial(jax.jit, static_argnums=(2,))
        def step(params, opt_state, kept_static, rng):
            # kept is static per compiled program (schedule granularity bounds
            # recompiles, like curriculum seqlen)
            g = jax.grad(loss_fn)(params, x, y, kept_static, rng)
            upd, opt_state = opt.update(g, opt_state)
            return optax.apply_updates(params, upd), opt_state

        losses, kept_seen = [], []
        for it in range(8):
            kept = sched.update_seq(it)
            kept_seen.append(kept)
            params, opt_state = step(params, opt_state, kept,
                                     jax.random.fold_in(key, 100 + it))
            losses.append(float(loss_fn(params, x, y, kept,
                                        jax.random.fold_in(key, 100 + it))))
        assert kept_seen[0] == 8 and kept_seen[-1] == 16   # ramp happened
        assert losses[-1] < losses[0]


class TestDataEfficiencySampling:
    """DataAnalyzer → indexed files → metric-based curriculum sampler →
    deepspeed_io → mid-epoch checkpoint resume (reference data_sampling/
    data_analyzer.py + data_sampler.py + indexed_dataset.py roles)."""

    def _dataset(self, n=64, vmax=500):
        rng = np.random.default_rng(0)
        lens = rng.integers(4, 33, size=n)
        return [{"input_ids": rng.integers(0, vmax, size=32).astype(np.int32),
                 "seqlen": int(l)} for l in lens]

    def test_indexed_dataset_roundtrip(self, tmp_path):
        from deepspeed_tpu.runtime.data_pipeline.indexed_dataset import (
            MMapIndexedDataset, MMapIndexedDatasetBuilder)

        b = MMapIndexedDatasetBuilder(str(tmp_path / "ds"), dtype=np.int32)
        rows = [np.arange(i + 1, dtype=np.int32) for i in range(5)]
        for r in rows:
            b.add_item(r)
        b.finalize()
        ds = MMapIndexedDataset(str(tmp_path / "ds"))
        assert len(ds) == 5
        for i, r in enumerate(rows):
            np.testing.assert_array_equal(ds[i], r)

    def test_analyzer_buckets_by_metric(self, tmp_path):
        from deepspeed_tpu.runtime.data_pipeline.data_analyzer import (
            DataAnalyzer, metric_paths)
        from deepspeed_tpu.runtime.data_pipeline.indexed_dataset import \
            MMapIndexedDataset

        data = self._dataset()
        # two workers map disjoint ranges, then reduce merges
        for w in range(2):
            DataAnalyzer(data, ["seqlen"], [lambda s: s["seqlen"]],
                         save_path=str(tmp_path), num_workers=2,
                         worker_id=w).run_map()
        DataAnalyzer(data, ["seqlen"], [lambda s: s["seqlen"]],
                     save_path=str(tmp_path), num_workers=2).run_reduce()
        p = metric_paths(str(tmp_path), "seqlen")
        i2m = MMapIndexedDataset(p["metric_path"])
        i2s = MMapIndexedDataset(p["sample_path"])
        s2m = MMapIndexedDataset(p["sample_to_metric_path"])
        assert len(s2m) == len(data)
        vals = [int(i2m[k][0]) for k in range(len(i2m))]
        assert vals == sorted(vals)
        covered = np.concatenate([i2s[k] for k in range(len(i2s))])
        assert sorted(covered.tolist()) == list(range(len(data)))
        for k in range(len(i2m)):
            for s in i2s[k]:
                assert data[int(s)]["seqlen"] == vals[k]

    def test_curriculum_sampler_ramp_and_resume(self, tmp_path):
        from deepspeed_tpu.runtime.data_pipeline.data_analyzer import (
            DataAnalyzer, metric_paths)
        from deepspeed_tpu.runtime.data_pipeline.data_sampler import \
            DeepSpeedDataSampler

        data = self._dataset()
        DataAnalyzer(data, ["seqlen"], [lambda s: s["seqlen"]],
                     save_path=str(tmp_path)).run()
        p = metric_paths(str(tmp_path), "seqlen")
        de = {"seed": 7, "data_sampling": {"num_epochs": 4,
              "curriculum_learning": {"enabled": True, "curriculum_metrics": {
                  "seqlen": {"index_to_sample_path": p["sample_path"],
                             "index_to_metric_path": p["metric_path"],
                             "difficulty_type": "value",
                             "min_difficulty": 8, "max_difficulty": 32,
                             "schedule_type": "fixed_linear",
                             "schedule_config": {"total_curriculum_step": 10,
                                                 "difficulty_step": 4}}}}}}
        s = DeepSpeedDataSampler(dict(de), len(data), global_batch_size=8)
        first = next(s)
        # difficulty ramp: the first batch only contains easy (short) samples
        assert all(data[int(i)]["seqlen"] <= 8 for i in first)
        batches = [next(s) for _ in range(3)]
        sd = s.state_dict()
        cont = [next(s) for _ in range(3)]
        # resume mid-epoch: a fresh sampler with the saved state continues
        # with the exact same index stream
        s2 = DeepSpeedDataSampler(dict(de), len(data), global_batch_size=8)
        s2.load_state_dict(sd)
        cont2 = [next(s2) for _ in range(3)]
        for a, b in zip(cont, cont2):
            np.testing.assert_array_equal(a, b)
        # late batches see hard samples
        for _ in range(8):
            last = next(s)
        assert any(data[int(i)]["seqlen"] > 16 for i in last)

    def test_sampler_resume_is_direct_not_replay(self, tmp_path, monkeypatch):
        """Resume restores rng + draw order directly — it must NOT re-scan
        the mmap index per consumed batch (ADVICE r3: counter-replay was
        O(consumed_steps x dataset) while the difficulty ramps). Legacy
        counter-only state dicts still take the replay path."""
        from deepspeed_tpu.runtime.data_pipeline.data_analyzer import (
            DataAnalyzer, metric_paths)
        from deepspeed_tpu.runtime.data_pipeline.data_sampler import \
            DeepSpeedDataSampler

        data = self._dataset()
        DataAnalyzer(data, ["seqlen"], [lambda s: s["seqlen"]],
                     save_path=str(tmp_path)).run()
        p = metric_paths(str(tmp_path), "seqlen")
        de = {"seed": 11, "data_sampling": {"num_epochs": 4,
              "curriculum_learning": {"enabled": True, "curriculum_metrics": {
                  "seqlen": {"index_to_sample_path": p["sample_path"],
                             "index_to_metric_path": p["metric_path"],
                             "difficulty_type": "value",
                             "min_difficulty": 8, "max_difficulty": 32,
                             "schedule_type": "fixed_linear",
                             "schedule_config": {"total_curriculum_step": 10,
                                                 "difficulty_step": 4}}}}}}
        s = DeepSpeedDataSampler(dict(de), len(data), global_batch_size=8)
        for _ in range(5):
            next(s)
        sd = s.state_dict()
        expect = [next(s) for _ in range(3)]

        s2 = DeepSpeedDataSampler(dict(de), len(data), global_batch_size=8)
        scans = []
        orig = DeepSpeedDataSampler._current_admitted
        monkeypatch.setattr(DeepSpeedDataSampler, "_current_admitted",
                            lambda self, d: (scans.append(d), orig(self, d))[1])
        s2.load_state_dict({k: v for k, v in sd.items()})
        assert scans == []          # direct restore: zero index scans
        for a, b in zip(expect, [next(s2) for _ in range(3)]):
            np.testing.assert_array_equal(a, b)

        # legacy counter-only dict: replay fallback still lands on the stream
        legacy = {k: sd[k] for k in ("curriculum_step", "consumed_samples",
                                     "position", "admitted_size")}
        s3 = DeepSpeedDataSampler(dict(de), len(data), global_batch_size=8)
        for a, b in zip(expect, (s3.load_state_dict(legacy),
                                 *[next(s3) for _ in range(3)])[1:]):
            np.testing.assert_array_equal(a, b)

        # a checkpoint from a different dataset is refused, not replayed
        s4 = DeepSpeedDataSampler(dict(de), len(data) + 8, global_batch_size=8)
        with pytest.raises(ValueError, match="different dataset"):
            s4.load_state_dict(dict(sd))

        # a changed global batch size is refused
        s5 = DeepSpeedDataSampler(dict(de), len(data), global_batch_size=16)
        with pytest.raises(ValueError, match="global_batch_size"):
            s5.load_state_dict(dict(sd))

        # a changed curriculum schedule is refused by the direct restore too
        import copy
        de2 = copy.deepcopy(de)
        de2["data_sampling"]["curriculum_learning"]["curriculum_metrics"][
            "seqlen"]["schedule_config"]["total_curriculum_step"] = 40
        s6 = DeepSpeedDataSampler(de2, len(data), global_batch_size=8)
        with pytest.raises(ValueError, match="schedule config changed"):
            s6.load_state_dict(dict(sd))

    def test_trains_through_deepspeed_io_and_resumes(self, tmp_path):
        from deepspeed_tpu.comm import comm
        from deepspeed_tpu.runtime.data_pipeline.data_analyzer import (
            DataAnalyzer, metric_paths)

        data = self._dataset(n=64, vmax=255)
        samples = [{"input_ids": d["input_ids"]} for d in data]
        DataAnalyzer(data, ["seqlen"], [lambda s: s["seqlen"]],
                     save_path=str(tmp_path / "idx")).run()
        p = metric_paths(str(tmp_path / "idx"), "seqlen")
        ds_cfg = {
            "train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "steps_per_print": 0,
            "data_efficiency": {"seed": 3, "data_sampling": {
                "num_epochs": 8,
                "curriculum_learning": {"enabled": True, "curriculum_metrics": {
                    "seqlen": {"index_to_sample_path": p["sample_path"],
                               "index_to_metric_path": p["metric_path"],
                               "difficulty_type": "percentile",
                               "min_difficulty": 25, "max_difficulty": 100,
                               "schedule_type": "fixed_linear",
                               "schedule_config": {"total_curriculum_step": 12,
                                                   "difficulty_step": 25}}}}}},
        }
        cfg = GPT2Config(vocab_size=256, n_positions=32, n_embd=32, n_layer=2,
                         n_head=4, dtype=jnp.float32, remat=False,
                         use_flash_attention=False)

        comm.cdb = None
        engine, _, loader, _ = deepspeed_tpu.initialize(
            model=GPT2Model(cfg), config=ds_cfg, training_data=samples)
        assert engine._data_sampler is not None
        it = iter(loader)
        for _ in range(3):
            loss = engine.train_batch(next(it))
        assert np.isfinite(float(loss))
        expected_next = engine._data_sampler.state_dict()
        engine.save_checkpoint(str(tmp_path / "ckpt"), tag="mid")

        # fresh engine + loader: resume must continue the sampler stream
        comm.cdb = None
        e2, _, _, _ = deepspeed_tpu.initialize(model=GPT2Model(cfg),
                                               config=ds_cfg)
        e2.load_checkpoint(str(tmp_path / "ckpt"), tag="mid")
        # an eval loader built FIRST must not bind the curriculum state
        eval_loader = e2.deepspeed_io(samples[:8], route="eval")
        assert getattr(e2, "_data_sampler", None) is None
        loader2 = e2.deepspeed_io(samples, route="train")
        assert e2._data_sampler is not None
        got = e2._data_sampler.state_dict()
        assert got["consumed_samples"] == expected_next["consumed_samples"]
        assert got["position"] == expected_next["position"]
        it2 = iter(loader2)
        l2 = e2.train_batch(next(it2))
        assert np.isfinite(float(l2))
