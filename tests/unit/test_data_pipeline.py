"""Data pipeline tests — reference tests/unit/runtime/test_data_efficiency
role: curriculum schedules, seqlen application during training, random-LTD
scheduler math + gather/scatter ops."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
from deepspeed_tpu.runtime.data_pipeline import (CurriculumScheduler,
                                                 RandomLTDScheduler,
                                                 apply_seqlen_curriculum,
                                                 random_ltd_gather,
                                                 random_ltd_scatter)
from deepspeed_tpu.runtime.data_pipeline.data_routing import random_ltd_sample


class TestCurriculumScheduler:
    def test_fixed_linear(self):
        s = CurriculumScheduler({"curriculum_type": "seqlen",
                                 "min_difficulty": 8, "max_difficulty": 64,
                                 "schedule_type": "fixed_linear",
                                 "schedule_config": {"total_curriculum_step": 100,
                                                     "difficulty_step": 8}})
        assert s.get_difficulty(0) == 8
        assert s.get_difficulty(100) == 64
        mid = s.get_difficulty(50)
        assert 8 < mid < 64 and mid % 8 == 0
        # monotone
        vals = [s.get_difficulty(t) for t in range(0, 120, 10)]
        assert vals == sorted(vals)

    def test_fixed_root(self):
        s = CurriculumScheduler({"min_difficulty": 8, "max_difficulty": 64,
                                 "schedule_type": "fixed_root",
                                 "schedule_config": {"total_curriculum_step": 100,
                                                     "difficulty_step": 8,
                                                     "root_degree": 2}})
        # sqrt schedule front-loads difficulty vs linear
        lin = CurriculumScheduler({"min_difficulty": 8, "max_difficulty": 64,
                                   "schedule_type": "fixed_linear",
                                   "schedule_config": {"total_curriculum_step": 100,
                                                       "difficulty_step": 8}})
        assert s.get_difficulty(25) >= lin.get_difficulty(25)
        assert s.get_difficulty(200) == 64

    def test_fixed_discrete(self):
        s = CurriculumScheduler({"min_difficulty": 2, "max_difficulty": 6,
                                 "schedule_type": "fixed_discrete",
                                 "schedule_config": {"difficulty": [2, 4, 6],
                                                     "max_step": [5, 10]}})
        assert s.get_difficulty(3) == 2
        assert s.get_difficulty(7) == 4
        assert s.get_difficulty(50) == 6

    def test_custom(self):
        s = CurriculumScheduler({"min_difficulty": 1, "max_difficulty": 10,
                                 "schedule_type": "custom"})
        s.set_custom_get_difficulty(lambda t: min(10, 1 + t))
        assert s.get_difficulty(3) == 4

    def test_state_roundtrip(self):
        s = CurriculumScheduler({"min_difficulty": 8, "max_difficulty": 64,
                                 "schedule_type": "fixed_linear",
                                 "schedule_config": {"total_curriculum_step": 100,
                                                     "difficulty_step": 8}})
        s.update_difficulty(50)
        sd = s.state_dict()
        s2 = CurriculumScheduler({"min_difficulty": 8, "max_difficulty": 64,
                                  "schedule_type": "fixed_linear",
                                  "schedule_config": {"total_curriculum_step": 100,
                                                      "difficulty_step": 8}})
        s2.load_state_dict(sd)
        assert s2.get_current_difficulty() == s.get_current_difficulty()


class TestApplySeqlen:
    def test_dict_batch(self):
        b = {"input_ids": np.zeros((4, 32), np.int32),
             "labels": np.zeros((4, 32), np.int32),
             "meta": np.zeros((4,))}
        out = apply_seqlen_curriculum(b, 16)
        assert out["input_ids"].shape == (4, 16)
        assert out["labels"].shape == (4, 16)
        assert out["meta"].shape == (4,)

    def test_engine_applies_curriculum(self):
        cfg = GPT2Config(vocab_size=256, n_positions=64, n_embd=32, n_layer=2,
                         n_head=2, remat=False, use_flash_attention=False)
        engine, *_ = deepspeed_tpu.initialize(
            model=GPT2Model(cfg),
            config={"train_batch_size": 8,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "curriculum_learning": {
                        "enabled": True, "curriculum_type": "seqlen",
                        "min_difficulty": 8, "max_difficulty": 32,
                        "schedule_type": "fixed_linear",
                        "schedule_config": {"total_curriculum_step": 4,
                                            "difficulty_step": 8}},
                    "steps_per_print": 0})
        assert engine.curriculum_learning_enabled()
        rng = np.random.RandomState(0)
        batch = {"input_ids": rng.randint(0, 256, size=(8, 32)).astype(np.int32)}
        difficulties = []
        for _ in range(5):
            loss = float(engine.train_batch(batch))
            difficulties.append(engine.curriculum_scheduler.get_current_difficulty())
        assert np.isfinite(loss)
        assert difficulties[0] == 8
        assert difficulties[-1] == 32
        assert difficulties == sorted(difficulties)


class TestRandomLTD:
    def _sched(self):
        return RandomLTDScheduler({
            "total_layer_num": 12, "random_ltd_layer_num": 8,
            "global_batch_size": 4,
            "schedule": {"min_value": 16, "max_value": 64,
                         "schedule_type": "fixed_linear",
                         "schedule_config": {"require_steps": 10,
                                             "seq_per_step": 16}}})

    def test_schedule_ramp(self):
        s = self._sched()
        assert s.get_value(0) == 16
        assert s.get_value(10) == 64
        assert s.update_seq(5) in range(16, 65, 16)
        assert s.consumed_layer_tokens > 0

    def test_token_accounting(self):
        s = self._sched()
        total = s.get_total_layer_tokens(3)
        # per step: B * (kept*ltd_layers + full*other_layers)
        assert total > 0

    def test_gather_scatter_roundtrip(self):
        rng = jax.random.PRNGKey(0)
        x = jnp.asarray(np.random.RandomState(0).randn(2, 16, 4).astype(np.float32))
        idx = random_ltd_sample(rng, 16, 8, 2)
        assert idx.shape == (2, 8)
        small = random_ltd_gather(x, idx)
        assert small.shape == (2, 8, 4)
        # scatter the gathered tokens back -> identical where kept
        back = random_ltd_scatter(small * 2.0, idx, x)
        picked = np.take_along_axis(np.asarray(back), np.asarray(idx)[..., None], axis=1)
        np.testing.assert_allclose(picked, np.asarray(small) * 2.0)

    def test_state_roundtrip(self):
        s = self._sched()
        s.update_seq(5)
        sd = s.state_dict()
        s2 = self._sched()
        s2.load_state_dict(sd)
        assert s2.get_current_seq() == s.get_current_seq()


class TestRandomLTDIntegration:
    def test_random_ltd_training_loop(self):
        """End-to-end random-LTD pattern (reference basic_layer
        RandomLayerTokenDrop role): middle 'layers' of a toy net train on a
        scheduled token subset; kept-count ramps and the loss still falls."""
        import optax

        sched = RandomLTDScheduler({
            "total_layer_num": 4, "random_ltd_layer_num": 2,
            "global_batch_size": 4,
            "schedule": {"min_value": 8, "max_value": 16,
                         "schedule_type": "fixed_linear",
                         "schedule_config": {"require_steps": 6,
                                             "seq_per_step": 8}}})
        D, T, B = 8, 16, 4
        key = jax.random.PRNGKey(0)
        params = {"w_in": jax.random.normal(key, (D, D)) * 0.3,
                  "w_mid": jax.random.normal(jax.random.fold_in(key, 1), (D, D)) * 0.3,
                  "w_out": jax.random.normal(jax.random.fold_in(key, 2), (D, D)) * 0.3}

        def loss_fn(params, x, y, kept, rng):
            h = jnp.tanh(x @ params["w_in"])
            # random-LTD "middle layer": process only `kept` tokens, scatter back
            idx = random_ltd_sample(rng, T, kept, B)
            small = random_ltd_gather(h, idx)
            small = jnp.tanh(small @ params["w_mid"])
            h = random_ltd_scatter(small, idx, h)
            out = h @ params["w_out"]
            return jnp.mean((out - y) ** 2)

        opt = optax.adam(1e-2)
        opt_state = opt.init(params)
        rng_np = np.random.RandomState(0)
        x = jnp.asarray(rng_np.randn(B, T, D).astype(np.float32))
        y = jnp.asarray(rng_np.randn(B, T, D).astype(np.float32))

        from functools import partial

        @partial(jax.jit, static_argnums=(2,))
        def step(params, opt_state, kept_static, rng):
            # kept is static per compiled program (schedule granularity bounds
            # recompiles, like curriculum seqlen)
            g = jax.grad(loss_fn)(params, x, y, kept_static, rng)
            upd, opt_state = opt.update(g, opt_state)
            return optax.apply_updates(params, upd), opt_state

        losses, kept_seen = [], []
        for it in range(8):
            kept = sched.update_seq(it)
            kept_seen.append(kept)
            params, opt_state = step(params, opt_state, kept,
                                     jax.random.fold_in(key, 100 + it))
            losses.append(float(loss_fn(params, x, y, kept,
                                        jax.random.fold_in(key, 100 + it))))
        assert kept_seen[0] == 8 and kept_seen[-1] == 16   # ramp happened
        assert losses[-1] < losses[0]
