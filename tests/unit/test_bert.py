"""BERT family: MLM numerics vs HF torch, masks, MLM training, TP serving
(the reference's headline benchmark family and kernel-parity baseline)."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models.bert import (IGNORE_INDEX, PRESETS, BertConfig,
                                       BertModel, synthetic_mlm_batch)
from deepspeed_tpu.module_inject.hf import load_bert, load_hf_model

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

VOCAB = 128


@pytest.fixture(scope="module")
def hf_bert():
    from transformers import BertConfig as HFConfig, BertForMaskedLM

    torch.manual_seed(0)
    cfg = HFConfig(vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
                   num_attention_heads=4, intermediate_size=64,
                   max_position_embeddings=64, type_vocab_size=2,
                   hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    return BertForMaskedLM(cfg).eval()


@pytest.fixture()
def ids():
    rng = np.random.RandomState(0)
    return rng.randint(4, VOCAB - 4, size=(2, 16)).astype(np.int32)


def _fp32(model):
    return BertModel(dataclasses.replace(model.config, dtype=jnp.float32,
                                         use_flash_attention=False))


class TestBertConversion:
    def test_logits_match_torch(self, hf_bert, ids):
        model, params = load_hf_model(hf_bert)
        assert isinstance(model, BertModel)
        model = _fp32(model)
        ours = np.asarray(model.apply(params, jnp.asarray(ids)))
        with torch.no_grad():
            theirs = hf_bert(torch.tensor(ids, dtype=torch.long)).logits.numpy()
        np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=2e-3)

    def test_token_types_and_attention_mask_match_torch(self, hf_bert, ids):
        model, params = load_hf_model(hf_bert)
        model = _fp32(model)
        tt = np.zeros_like(ids)
        tt[:, 8:] = 1
        am = np.ones_like(ids)
        am[:, 12:] = 0        # padded tail
        ours = np.asarray(model.apply(params, jnp.asarray(ids),
                                      token_type_ids=jnp.asarray(tt),
                                      attention_mask=jnp.asarray(am)))
        with torch.no_grad():
            theirs = hf_bert(torch.tensor(ids, dtype=torch.long),
                             token_type_ids=torch.tensor(tt, dtype=torch.long),
                             attention_mask=torch.tensor(am, dtype=torch.long)
                             ).logits.numpy()
        # positions attending only to unpadded tokens must agree
        np.testing.assert_allclose(ours[:, :12], theirs[:, :12],
                                   rtol=2e-3, atol=2e-3)

    def test_mlm_loss_matches_torch(self, hf_bert, ids):
        model, params = load_hf_model(hf_bert)
        model = _fp32(model)
        labels = ids.copy().astype(np.int32)
        labels[:, ::3] = IGNORE_INDEX
        ours = float(model.loss(params, {"input_ids": jnp.asarray(ids),
                                         "labels": jnp.asarray(labels)}))
        with torch.no_grad():
            theirs = float(hf_bert(torch.tensor(ids, dtype=torch.long),
                                   labels=torch.tensor(labels, dtype=torch.long)
                                   ).loss)
        assert abs(ours - theirs) < 2e-3, (ours, theirs)


class TestBertNative:
    def test_mlm_train_through_initialize(self):
        cfg = dataclasses.replace(PRESETS["bert-tiny"],
                                  use_flash_attention=False)
        engine, *_ = deepspeed_tpu.initialize(
            model=BertModel(cfg),
            config={"train_batch_size": 8,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                    "bf16": {"enabled": True},
                    "zero_optimization": {"stage": 2},
                    "steps_per_print": 0})
        batch = synthetic_mlm_batch(8, 64, cfg.vocab_size)
        losses = [float(engine.train_batch(batch)) for _ in range(6)]
        assert losses[-1] < losses[0], losses

    def test_masked_gather_loss_matches_full(self):
        """max_predictions_per_seq (gather_indexes) must not change the loss
        as long as every row has ≤ maxp labels; scan_unroll must not either."""
        cfg = dataclasses.replace(PRESETS["bert-tiny"], dtype=jnp.float32,
                                  use_flash_attention=False)
        batch = synthetic_mlm_batch(4, 64, cfg.vocab_size, seed=3)
        assert int((batch["labels"] != IGNORE_INDEX).sum(axis=1).max()) <= 20
        params = BertModel(cfg).init_params(jax.random.PRNGKey(0))
        full = float(BertModel(cfg).loss(params, batch))
        gathered = float(BertModel(dataclasses.replace(
            cfg, max_predictions_per_seq=20)).loss(params, batch))
        unrolled = float(BertModel(dataclasses.replace(
            cfg, scan_unroll=2)).loss(params, batch))
        np.testing.assert_allclose(full, gathered, rtol=1e-6)
        np.testing.assert_allclose(full, unrolled, rtol=1e-6)
        # honest MFU: gathered config reports fewer flops than full
        g = dataclasses.replace(cfg, max_predictions_per_seq=20)
        assert g.flops_per_token(64) < cfg.flops_per_token(64)

    def test_mlm_overflow_debug_warning(self, monkeypatch):
        """DS_DEBUG_MLM=1 asserts the data-side invariant: a row carrying
        more labels than max_predictions_per_seq warns once (the gathered
        head silently drops the excess — ADVICE r3)."""
        import deepspeed_tpu.models.bert as bert_mod

        monkeypatch.setenv("DS_DEBUG_MLM", "1")
        monkeypatch.setattr(bert_mod, "_mlm_overflow_warned", False)
        warnings = []
        from deepspeed_tpu.utils.logging import logger as ds_logger
        monkeypatch.setattr(ds_logger, "warning",
                            lambda msg, *a: warnings.append(msg))
        cfg = dataclasses.replace(PRESETS["bert-tiny"], dtype=jnp.float32,
                                  use_flash_attention=False,
                                  max_predictions_per_seq=4)
        batch = synthetic_mlm_batch(2, 64, cfg.vocab_size, seed=3)
        assert int((batch["labels"] != IGNORE_INDEX).sum(axis=1).max()) > 4
        params = BertModel(cfg).init_params(jax.random.PRNGKey(0))
        loss = BertModel(cfg).loss(params, batch)
        jax.block_until_ready(loss)
        jax.effects_barrier()
        assert any("max_predictions_per_seq" in w for w in warnings)
        # capped batch: no warning
        warnings.clear()
        monkeypatch.setattr(bert_mod, "_mlm_overflow_warned", False)
        ok = synthetic_mlm_batch(2, 64, cfg.vocab_size, seed=3,
                                 max_predictions=4)
        loss = BertModel(cfg).loss(params, ok)
        jax.block_until_ready(loss)
        jax.effects_barrier()
        assert warnings == []

    def test_num_params_matches_tree(self):
        cfg = PRESETS["bert-tiny"]
        params = BertModel(cfg).init_params(jax.random.PRNGKey(0))
        n = sum(x.size for x in jax.tree.leaves(params))
        assert n == cfg.num_params()

    def test_bert_large_param_count(self):
        assert abs(PRESETS["bert-large"].num_params() - 335e6) / 335e6 < 0.02

    def test_tp2_logits_match_tp1(self, hf_bert, ids):
        from deepspeed_tpu.comm import comm
        from deepspeed_tpu.parallel.topology import build_mesh

        model, params = load_hf_model(hf_bert)
        model = _fp32(model)
        outs = {}
        for tp in (1, 2):
            comm.cdb = None
            mesh = build_mesh(axis_dims={"pipe": 1, "data": 8 // tp, "expert": 1,
                                         "seq": 1, "tensor": tp})
            comm.init_distributed(mesh=mesh, verbose=False)
            engine = deepspeed_tpu.init_inference(
                model, config={"dtype": "fp32", "max_out_tokens": 64},
                params=params, mesh=mesh)
            outs[tp] = np.asarray(engine.forward(ids))
        np.testing.assert_allclose(outs[2], outs[1], rtol=1e-5, atol=1e-5)
