"""Inference tests (reference: tests/unit/inference/test_inference.py sweeps
models × dtype × injection; here: KV-cache decode == full forward, generate
determinism, TP sharding, AutoTP classification)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm import comm
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model, synthetic_lm_batch
from deepspeed_tpu.module_inject.auto_tp import AutoTP

TINY = GPT2Config(vocab_size=512, n_positions=128, n_embd=64, n_layer=2, n_head=4,
                  dtype=jnp.float32, remat=False, use_flash_attention=False)


def test_prefill_decode_matches_full_forward():
    """Incremental decode must reproduce teacher-forced logits exactly."""
    model = GPT2Model(TINY)
    params = model.init_params(jax.random.PRNGKey(0))
    ids = jnp.asarray(synthetic_lm_batch(2, 16, TINY.vocab_size)["input_ids"])

    full_logits = model.apply(params, ids)  # (B, T, V)

    cache = model.init_cache(2, 32)
    logits_p, cache = model.prefill(params, ids[:, :8], cache)
    np.testing.assert_allclose(np.asarray(logits_p), np.asarray(full_logits[:, 7]),
                               rtol=1e-4, atol=1e-4)
    # feed the true next tokens one by one
    for t in range(8, 16):
        logits_d, cache = model.decode_step(params, ids[:, t], cache)
        np.testing.assert_allclose(np.asarray(logits_d), np.asarray(full_logits[:, t]),
                                   rtol=1e-4, atol=1e-4)


def test_generate_greedy():
    comm.cdb = None
    model = GPT2Model(TINY)
    engine = deepspeed_tpu.init_inference(model, config={"dtype": "float32",
                                                         "max_out_tokens": 128})
    prompt = np.asarray(synthetic_lm_batch(2, 8, TINY.vocab_size)["input_ids"])
    out = engine.generate(prompt, max_new_tokens=8)
    assert out.shape == (2, 16)
    out2 = engine.generate(prompt, max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))  # greedy = deterministic
    np.testing.assert_array_equal(np.asarray(out[:, :8]), prompt)


def test_generate_sampling_respects_seed():
    comm.cdb = None
    model = GPT2Model(TINY)
    engine = deepspeed_tpu.init_inference(model, config={"dtype": "float32",
                                                         "max_out_tokens": 128})
    prompt = np.asarray(synthetic_lm_batch(1, 4, TINY.vocab_size)["input_ids"])
    a = engine.generate(prompt, max_new_tokens=6, do_sample=True, temperature=1.0, seed=1)
    b = engine.generate(prompt, max_new_tokens=6, do_sample=True, temperature=1.0, seed=1)
    c = engine.generate(prompt, max_new_tokens=6, do_sample=True, temperature=1.0, seed=2)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))


def test_inference_tp2_matches_tp1():
    comm.cdb = None
    model = GPT2Model(TINY)
    params = model.init_params(jax.random.PRNGKey(0))
    e1 = deepspeed_tpu.init_inference(model, config={"dtype": "float32",
                                                     "max_out_tokens": 128}, params=params)
    prompt = np.asarray(synthetic_lm_batch(2, 8, TINY.vocab_size)["input_ids"])
    out1 = np.asarray(e1.generate(prompt, max_new_tokens=8))

    comm.cdb = None
    e2 = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32", "tensor_parallel": {"tp_size": 2},
                       "max_out_tokens": 128}, params=params)
    assert e2.mp_world_size == 2
    qkv = e2.params["blocks"]["qkv_w"]
    assert qkv.addressable_shards[0].data.shape[-1] == qkv.shape[-1] // 2
    out2 = np.asarray(e2.generate(prompt, max_new_tokens=8))
    np.testing.assert_array_equal(out1, out2)


def test_serve_training_checkpoint_at_different_tp(tmp_path):
    """Serving TP reshard (reference inference/engine.py:336-506): a
    checkpoint SAVED at tp=4 must serve at tp=2 and tp=1 with identical
    logits — init_inference loads the params subtree straight into the
    serving shardings."""
    from deepspeed_tpu.parallel.topology import build_mesh

    comm.cdb = None
    mesh4 = build_mesh(axis_dims={"pipe": 1, "data": 2, "expert": 1,
                                  "seq": 1, "tensor": 4})
    comm.init_distributed(mesh=mesh4, verbose=False)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2Model(TINY),
        config={"train_batch_size": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 1}, "steps_per_print": 0})
    batch = synthetic_lm_batch(8, 16, TINY.vocab_size, seed=3)
    engine.train_batch(batch)
    engine.save_checkpoint(str(tmp_path), tag="tp4")

    ids = np.asarray(synthetic_lm_batch(2, 12, TINY.vocab_size, seed=4)["input_ids"])
    trained = jax.tree.map(np.asarray, engine.state.params)
    base = np.asarray(GPT2Model(TINY).apply(trained, jnp.asarray(ids)))

    for tp in (1, 2):
        comm.cdb = None
        mesh = build_mesh(axis_dims={"pipe": 1, "data": 8 // tp, "expert": 1,
                                     "seq": 1, "tensor": tp})
        comm.init_distributed(mesh=mesh, verbose=False)
        eng = deepspeed_tpu.init_inference(
            GPT2Model(TINY),
            config={"dtype": "fp32", "checkpoint": str(tmp_path),
                    "ckpt_config": {"tag": "tp4"}, "max_out_tokens": 64},
            mesh=mesh)
        out = np.asarray(eng.forward(ids))
        np.testing.assert_allclose(out, base, rtol=1e-5, atol=1e-5)


def test_max_out_tokens_guard():
    comm.cdb = None
    model = GPT2Model(TINY)
    engine = deepspeed_tpu.init_inference(model, config={"dtype": "float32",
                                                         "max_out_tokens": 16})
    prompt = np.asarray(synthetic_lm_batch(1, 8, TINY.vocab_size)["input_ids"])
    with pytest.raises(ValueError):
        engine.generate(prompt, max_new_tokens=32)


def test_autotp_classifies_hf_style_tree():
    shapes = {
        "transformer": {
            "h": {"0": {
                "attn": {"c_attn": {"kernel": jax.ShapeDtypeStruct((64, 192), jnp.float32)},
                         "c_proj": {"kernel": jax.ShapeDtypeStruct((64, 64), jnp.float32)}},
                "mlp": {"c_fc": {"kernel": jax.ShapeDtypeStruct((64, 256), jnp.float32)},
                        "c_proj": {"kernel": jax.ShapeDtypeStruct((256, 64), jnp.float32)}},
            }},
            "wte": {"embedding": jax.ShapeDtypeStruct((512, 64), jnp.float32)},
        }
    }
    specs = AutoTP.infer_specs(shapes)
    h0 = specs["transformer"]["h"]["0"]
    assert h0["attn"]["c_attn"]["kernel"] == jax.sharding.PartitionSpec(None, "tensor")
    assert h0["attn"]["c_proj"]["kernel"] == jax.sharding.PartitionSpec("tensor", None)
    assert h0["mlp"]["c_fc"]["kernel"] == jax.sharding.PartitionSpec(None, "tensor")
    assert h0["mlp"]["c_proj"]["kernel"] == jax.sharding.PartitionSpec("tensor", None)


def test_generate_varying_batch_and_prompt_len():
    """Regression: the compiled generate must re-specialize when batch size or
    prompt length changes between calls (B/T derived inside the trace)."""
    comm.cdb = None
    model = GPT2Model(TINY)
    engine = deepspeed_tpu.init_inference(model, config={"dtype": "float32",
                                                         "max_out_tokens": 128})
    p2 = np.asarray(synthetic_lm_batch(2, 8, TINY.vocab_size)["input_ids"])
    p4 = np.asarray(synthetic_lm_batch(4, 6, TINY.vocab_size)["input_ids"])
    out2 = engine.generate(p2, max_new_tokens=4)
    out4 = engine.generate(p4, max_new_tokens=4)
    assert out2.shape == (2, 12)
    assert out4.shape == (4, 10)


def test_injection_policy_refines_model_specs():
    """A policy entry overrides only matched leaves; everything else keeps the
    model's own partition specs (not AutoTP name patterns)."""
    from jax.sharding import PartitionSpec as P

    model = GPT2Model(TINY)
    base = model.param_partition_specs()
    shapes = jax.eval_shape(model.init_params, jax.random.PRNGKey(0))
    refined = AutoTP.infer_specs(shapes, policy={"lm_head|wte": "replicate"},
                                 base_specs=base)
    flat_base = jax.tree_util.tree_flatten_with_path(base, is_leaf=lambda x: isinstance(x, P))[0]
    flat_ref = jax.tree_util.tree_flatten_with_path(refined, is_leaf=lambda x: isinstance(x, P))[0]
    changed = unchanged_kept = 0
    for (path_b, sb), (path_r, sr) in zip(flat_base, flat_ref):
        name = "/".join(str(getattr(p, "key", p)) for p in path_b).lower()
        if "wte" in name:
            assert sr == P(), f"{name} should be replicated, got {sr}"
            changed += 1
        else:
            assert sr == sb, f"{name} changed unexpectedly: {sb} -> {sr}"
            unchanged_kept += 1
    assert changed >= 1 and unchanged_kept > 0
