"""Weight quantization tests — reference csrc/quantization + GroupQuantizer
(module_inject/replace_module.py:143) role: int8/int4 per-group weights,
dequant-on-the-fly serving within tolerance of bf16, memory halved."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.comm import comm
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model, synthetic_lm_batch
from deepspeed_tpu.ops.quantizer import (Quantizer, dequantize_params,
                                         dequantize_tensor, is_quantized_leaf,
                                         quantize_params, quantize_tensor,
                                         quantized_nbytes)

TINY = GPT2Config(vocab_size=512, n_positions=64, n_embd=64, n_layer=2, n_head=4,
                  dtype=jnp.float32, remat=False, use_flash_attention=False)


class TestQuantizeTensor:
    @pytest.mark.parametrize("bits", [8, 4])
    def test_roundtrip_error_bound(self, bits):
        rng = np.random.RandomState(0)
        w = jnp.asarray(rng.randn(128, 64).astype(np.float32))
        leaf = quantize_tensor(w, num_bits=bits, group_size=64)
        back = dequantize_tensor(leaf)
        assert back.shape == w.shape and back.dtype == w.dtype
        err = float(jnp.max(jnp.abs(back - w)))
        # symmetric rounding: max error = scale/2 per group
        bound = 0.5 * float(jnp.max(leaf.scale)) * 1.01
        assert err <= bound, (err, bound)

    def test_asymmetric_beats_symmetric_on_shifted_data(self):
        rng = np.random.RandomState(1)
        w = jnp.asarray((rng.rand(128, 32) + 3.0).astype(np.float32))  # all ~[3,4]
        sym = dequantize_tensor(quantize_tensor(w, 8, 64, symmetric=True))
        asym = dequantize_tensor(quantize_tensor(w, 8, 64, symmetric=False))
        assert float(jnp.mean(jnp.abs(asym - w))) < float(jnp.mean(jnp.abs(sym - w)))

    def test_int4_packs_half_bytes(self):
        w = jnp.ones((64, 16), jnp.float32)
        leaf = quantize_tensor(w, num_bits=4, group_size=32)
        assert leaf.q.shape == (2, 16, 16)  # group dim halved by packing

    def test_quantizer_op_surface(self):
        q = Quantizer(q_groups=4, num_bits=8)
        w = jnp.asarray(np.random.RandomState(2).randn(64, 32).astype(np.float32))
        back = q.dequantize(q.quantize(w))
        assert float(jnp.max(jnp.abs(back - w))) < 0.05

    def test_quantizer_1d_buffer(self):
        q = Quantizer(q_groups=4, num_bits=8)
        w = jnp.asarray(np.random.RandomState(3).randn(256).astype(np.float32))
        back = q.dequantize(q.quantize(w))
        assert back.shape == w.shape
        assert float(jnp.max(jnp.abs(back - w))) < 0.05


class TestQuantizeParams:
    def test_tree_transform_and_memory(self):
        model = GPT2Model(TINY)
        params = model.init_params(jax.random.PRNGKey(0))
        params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)
        before = sum(x.nbytes for x in jax.tree.leaves(params))
        qp = quantize_params(params, num_bits=8, min_numel=1024)
        leaves = jax.tree.leaves(qp, is_leaf=is_quantized_leaf)
        assert any(is_quantized_leaf(l) for l in leaves)
        # embeddings (incl. tied head) / ln / bias excluded
        assert not is_quantized_leaf(qp["wte"])
        assert not is_quantized_leaf(qp["wpe"])
        assert not is_quantized_leaf(qp["blocks"]["ln1_g"])
        assert is_quantized_leaf(qp["blocks"]["qkv_w"])
        after = quantized_nbytes(qp)
        # tiny model: embeddings are a big share and stay bf16; projection
        # weights (the quantized part) halve
        assert after < 0.75 * before, (before, after)
        back = dequantize_params(qp, jnp.bfloat16)
        assert back["blocks"]["qkv_w"].shape == params["blocks"]["qkv_w"].shape
        assert back["blocks"]["qkv_w"].dtype == jnp.bfloat16


class TestInt8Serving:
    def test_int8_generate_close_to_bf16(self):
        comm.cdb = None
        model = GPT2Model(TINY)
        params = model.init_params(jax.random.PRNGKey(0))
        ids = np.asarray(synthetic_lm_batch(2, 12, TINY.vocab_size)["input_ids"])

        ref_engine = deepspeed_tpu.init_inference(
            model, config={"dtype": "fp32", "max_out_tokens": 64}, params=params)
        ref_logits = np.asarray(ref_engine.forward(ids))
        ref_out = np.asarray(ref_engine.generate(ids, max_new_tokens=8))

        comm.cdb = None
        q_engine = deepspeed_tpu.init_inference(
            model, config={"dtype": "int8", "max_out_tokens": 64,
                           "quant": {"weight": {"quantized_initialization":
                                                {"min_numel": 1024}}}},
            params=params)
        q_logits = np.asarray(q_engine.forward(ids))
        q_out = np.asarray(q_engine.generate(ids, max_new_tokens=8))

        # projection weights halve vs bf16 serving; embeddings stay bf16
        from deepspeed_tpu.ops.quantizer import quantized_nbytes
        bf16_equiv = sum(int(np.prod(x.shape)) * 2 for x in jax.tree.leaves(params))
        assert quantized_nbytes(q_engine.params) < 0.75 * bf16_equiv
        # logits close; generation shape identical and prompts preserved
        rel = np.abs(q_logits - ref_logits).max() / (np.abs(ref_logits).max() + 1e-9)
        assert rel < 0.15, rel
        assert q_out.shape == ref_out.shape
        assert (q_out[:, :12] == ids).all()
