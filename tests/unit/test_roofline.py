"""ds_roofline tests — analytic roofline over the compiled HLO.

Tier-1 keeps the cheap spine: the hlo_model compute-op units (dot /
fusion / tuple-fusion / convolution / while-body-once / convert — the
HloCostAnalysis counting conventions, probe-calibrated), the chips
table pinned against the accelerator's peak dicts, the pure analysis
math (bound classification, mfu ceiling, decode MBU units), ONE
gpt2-tiny ZeRO-3 engine on the 8-device mesh (regex flops vs
``compiled.cost_analysis()`` within 5%, the ledger hoist, the top
memory-bound fusion named), the mfu_gap gate matrix, the no-jax
``bin/ds_roofline`` subprocess, the schema cross-fields, and the strict
no-op sys.modules assertion.
"""

import json
import os
import subprocess
import sys

import jax
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model, synthetic_lm_batch

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

ROOF_MOD = "deepspeed_tpu.analysis.roofline"
CHIPS_MOD = "deepspeed_tpu.analysis.chips"


def _reset():
    from deepspeed_tpu.comm import comm
    from deepspeed_tpu.sharding import mesh as smesh
    from deepspeed_tpu.sharding.jit import reset_program_table

    comm.cdb = None
    smesh.reset_global_mesh()
    reset_program_table()


def _mk_engine(extra=None, stage=3, bs=8):
    cfg = GPT2Config(vocab_size=256, n_positions=64, n_embd=64,
                     n_layer=2, n_head=4, use_flash_attention=False)
    dcfg = {"train_batch_size": bs,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": stage,
                                  "stage3_param_persistence_threshold": 0},
            "tpu": {"data": 8}, "steps_per_print": 0}
    dcfg.update(extra or {})
    engine, _, _, _ = deepspeed_tpu.initialize(model=GPT2Model(cfg),
                                               config=dcfg)
    return engine, cfg


# A hand-written post-GSPMD-shaped module: one dot (annotated contracting
# dims), one fusion calling an add+tanh computation, one convert root.
DOT_FUSION_TEXT = """\
HloModule test_module, is_scheduled=true, entry_computation_layout=\
{(f32[64,128]{1,0}, f32[128,64]{1,0})->bf16[64,64]{1,0}}, num_partitions=8

%fused_add_tanh (p0.1: f32[64,64], p1.1: f32[64,64]) -> f32[64,64] {
  %p0.1 = f32[64,64]{1,0} parameter(0)
  %p1.1 = f32[64,64]{1,0} parameter(1)
  %add.1 = f32[64,64]{1,0} add(f32[64,64]{1,0} %p0.1, f32[64,64]{1,0} %p1.1)
  ROOT %tanh.1 = f32[64,64]{1,0} tanh(f32[64,64]{1,0} %add.1)
}

ENTRY %main (a: f32[64,128], b: f32[128,64]) -> bf16[64,64] {
  %a = f32[64,128]{1,0} parameter(0)
  %b = f32[128,64]{1,0} parameter(1)
  %dot.2 = f32[64,64]{1,0} dot(f32[64,128]{1,0} %a, f32[128,64]{1,0} %b), \
lhs_contracting_dims={1}, rhs_contracting_dims={0}, \
metadata={op_name="jit(step)/dot_general" source_file="model.py" \
source_line=42}
  %fusion.1 = f32[64,64]{1,0} fusion(f32[64,64]{1,0} %dot.2, \
f32[64,64]{1,0} %dot.2), kind=kLoop, calls=%fused_add_tanh
  ROOT %convert.3 = bf16[64,64]{1,0} convert(f32[64,64]{1,0} %fusion.1)
}
"""


# -------------------------------------------------- hlo_model compute units
@pytest.mark.analysis
class TestHloComputeModel:
    def _ops(self, text):
        from deepspeed_tpu.analysis.hlo_model import parse_hlo_module

        m = parse_hlo_module(text)
        return m, {op.name: op for op in m.compute_ops}

    def test_dot_fusion_convert_costs(self):
        """The probe-calibrated conventions: dot = 2·out·contract (from
        lhs_contracting_dims over the lhs OPERAND shape), fusion rolls up
        its called computation's flops/transcendentals but only EXTERNAL
        bytes, convert is 1 flop/element (mixed-precision ZeRO-3 carries
        millions of cast elements — omitting it once put the model 16%
        under XLA), tanh is a transcendental and NEVER flops."""
        m, ops = self._ops(DOT_FUSION_TEXT)
        assert set(ops) == {"dot.2", "fusion.1", "convert.3"}
        dot = ops["dot.2"]
        assert dot.flops == 2 * 64 * 64 * 128
        assert dot.bytes == (64 * 64 * 4) + (64 * 128 * 4 + 128 * 64 * 4)
        assert dot.metadata_op == "jit(step)/dot_general"
        assert dot.source_line == "model.py:42"
        fus = ops["fusion.1"]
        assert fus.flops == 64 * 64            # the fused add
        assert fus.transcendentals == 64 * 64  # the fused tanh
        assert fus.bytes == 3 * (64 * 64 * 4)  # 2 operands + result ONLY
        conv = ops["convert.3"]
        assert conv.flops == 64 * 64
        assert conv.bytes == 64 * 64 * 4 + 64 * 64 * 2
        assert m.total_flops() == dot.flops + fus.flops + conv.flops
        assert m.total_transcendentals() == 64 * 64
        # fused-computation interiors never appear as their own regions
        assert all(op.computation == "main" for op in m.compute_ops)

    def test_tuple_result_fusion(self):
        """A multi-output fusion: tuple result bytes, callee flops and
        transcendentals both roll up."""
        text = """\
HloModule tup, num_partitions=1

%fused_two (p: f32[128]) -> (f32[128], f32[128]) {
  %p = f32[128]{0} parameter(0)
  %m = f32[128]{0} multiply(f32[128]{0} %p, f32[128]{0} %p)
  %e = f32[128]{0} exponential(f32[128]{0} %p)
  ROOT %t = (f32[128]{0}, f32[128]{0}) tuple(f32[128]{0} %m, f32[128]{0} %e)
}

ENTRY %main2 (x: f32[128]) -> (f32[128], f32[128]) {
  %x = f32[128]{0} parameter(0)
  ROOT %fusion.9 = (f32[128]{0}, f32[128]{0}) fusion(f32[128]{0} %x), \
kind=kLoop, calls=%fused_two
}
"""
        _, ops = self._ops(text)
        [fus] = ops.values()
        assert fus.opcode == "fusion"
        assert fus.flops == 128 and fus.transcendentals == 128
        assert fus.bytes == 2 * 128 * 4 + 128 * 4   # tuple result + operand

    def test_convolution_dim_labels(self):
        """conv = 2 · out_elems · (kernel_elems / out_features), the
        output-feature position read from dim_labels."""
        text = """\
HloModule conv, num_partitions=1

ENTRY %c (in: f32[1,8,8,16], k: f32[3,3,16,32]) -> f32[1,8,8,32] {
  %in = f32[1,8,8,16]{3,2,1,0} parameter(0)
  %k = f32[3,3,16,32]{3,2,1,0} parameter(1)
  ROOT %conv = f32[1,8,8,32]{3,2,1,0} convolution(f32[1,8,8,16]{3,2,1,0} \
%in, f32[3,3,16,32]{3,2,1,0} %k), window={size=3x3 pad=1_1x1_1}, \
dim_labels=b01f_01io->b01f
}
"""
        _, ops = self._ops(text)
        # 2 * (1*8*8*32) * (3*3*16) = 589824
        assert ops["conv"].flops == 2 * 2048 * 144

    def test_while_body_counted_once(self):
        """while itself is zero-cost; its body/cond computations appear
        as regions ONCE (HloCostAnalysis shares the convention, so the
        live cross-check stays a ratio of like with like)."""
        text = """\
HloModule wh, num_partitions=1

%body (s: (s32[], f32[256])) -> (s32[], f32[256]) {
  %s = (s32[], f32[256]{0}) parameter(0)
  %i = s32[] get-tuple-element((s32[], f32[256]{0}) %s), index=0
  %v = f32[256]{0} get-tuple-element((s32[], f32[256]{0}) %s), index=1
  %one = s32[] constant(1)
  %i2 = s32[] add(s32[] %i, s32[] %one)
  %v2 = f32[256]{0} multiply(f32[256]{0} %v, f32[256]{0} %v)
  ROOT %r = (s32[], f32[256]{0}) tuple(s32[] %i2, f32[256]{0} %v2)
}

%cond (s2: (s32[], f32[256])) -> pred[] {
  %s2 = (s32[], f32[256]{0}) parameter(0)
  %i3 = s32[] get-tuple-element((s32[], f32[256]{0}) %s2), index=0
  %n = s32[] constant(4)
  ROOT %lt = pred[] compare(s32[] %i3, s32[] %n), direction=LT
}

ENTRY %main3 (x0: (s32[], f32[256])) -> (s32[], f32[256]) {
  %x0 = (s32[], f32[256]{0}) parameter(0)
  ROOT %w = (s32[], f32[256]{0}) while((s32[], f32[256]{0}) %x0), \
condition=%cond, body=%body
}
"""
        m, _ = self._ops(text)
        assert m.total_flops() == 1 + 256 + 1   # add + multiply + compare
        comps = {op.computation for op in m.compute_ops}
        assert comps == {"body", "cond"}

    def test_collectives_still_parse_alongside(self):
        """The compute extension must not disturb the ds_xray spine."""
        from deepspeed_tpu.analysis.hlo_model import parse_hlo_module

        text = ("HloModule m, is_scheduled=true, num_partitions=8\n"
                "ENTRY %e (x: f32[128]) -> f32[128] {\n"
                "  %x = f32[128]{0} parameter(0)\n"
                "  %n = f32[128]{0} negate(f32[128]{0} %x)\n"
                "  ROOT %ar = f32[128]{0} all-reduce(f32[128]{0} %n), "
                "channel_id=1, replica_groups=[1,8]<=[8], "
                "use_global_device_ids=true, to_apply=%add\n}\n")
        m = parse_hlo_module(text)
        assert len(m.collectives) == 1
        assert m.collectives[0].kind == "all-reduce"
        assert m.total_flops() == 128           # the negate

    def test_live_probe_matches_cost_analysis(self):
        """One single-device compile: the regex model's flops land
        within 0.1% of ``cost_analysis()`` and transcendentals match
        EXACTLY (dot + elementwise + tanh + convert fusions — shared
        counting conventions, not approximate agreement; the flops side
        tolerates XLA's off-by-one on scalar-reduce corner cases)."""
        import jax.numpy as jnp

        from deepspeed_tpu.analysis.hlo_model import parse_hlo_module

        def f(a, b):
            h = jnp.tanh(a @ b)
            return (h.astype(jnp.bfloat16).astype(jnp.float32) * 2.0).sum()

        c = jax.jit(f).lower(jnp.ones((32, 64)), jnp.ones((64, 16))).compile()
        ca = c.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        m = parse_hlo_module(c.as_text())
        xla_flops = float(ca.get("flops", 0))
        assert xla_flops > 0
        assert abs(m.total_flops() - xla_flops) <= 0.001 * xla_flops
        assert m.total_transcendentals() == int(ca.get("transcendentals", 0))


# ----------------------------------------------------------------- chips
@pytest.mark.analysis
class TestChips:
    def test_table_pinned_to_accelerator_peaks(self):
        """chips.py restates tpu_accelerator's dicts without the jax
        import — the two tables must never drift."""
        from deepspeed_tpu.accelerator.tpu_accelerator import (_PEAK_FLOPS,
                                                               _PEAK_HBM_BW)
        from deepspeed_tpu.analysis.chips import resolve_chip

        for gen, flops in _PEAK_FLOPS.items():
            spec = resolve_chip(gen if gen != "cpu" else "cpu-sim")
            assert spec.peak_flops == flops, gen
            assert spec.hbm_bytes_per_s == _PEAK_HBM_BW[gen], gen

    def test_aliases_and_unknown(self):
        from deepspeed_tpu.analysis.chips import resolve_chip

        assert resolve_chip("v5litepod").name == "v5e"
        assert resolve_chip("V5E").name == "v5e"
        assert resolve_chip("cpu").name == "cpu-sim"
        with pytest.raises(KeyError, match="v5e"):
            resolve_chip("h100")

    def test_detect_and_fp32_halving(self):
        from deepspeed_tpu.analysis.chips import (detect_chip_name,
                                                  resolve_chip)

        assert detect_chip_name("TPU v5 lite", "tpu") == "v5e"
        assert detect_chip_name("", "cpu") == "cpu-sim"
        spec = resolve_chip("v4")
        assert spec.peak_flops_for("float32") == spec.peak_flops / 2
        assert spec.peak_flops_for("bf16") == spec.peak_flops


# --------------------------------------------------------- analysis math
@pytest.mark.analysis
class TestRooflineMath:
    def test_bound_classification_and_ceiling(self):
        from deepspeed_tpu.analysis.roofline import analyze_hlo_text

        rep = analyze_hlo_text(DOT_FUSION_TEXT, chip="v5e",
                               program="fixture")
        by = {r.name: r for r in rep.regions}
        # dot intensity 1M flops / 80KB = 12.8 fl/B < v5e ridge (~240):
        # everything here is memory-bound on a real chip
        assert by["dot.2"].bound == "memory"
        assert rep.top_memory_bound() is not None
        assert 0.0 < rep.mfu_ceiling <= 1.0
        assert rep.predicted_step_s > 0
        assert abs(rep.memory_bound_share() - 1.0) < 1e-9
        # regions sorted by predicted time, the dot's bytes dominate
        assert rep.regions[0].name == "dot.2"
        # render names the program, the chip, and the top region
        text = rep.render(top_k=2)
        assert "fixture" in text and "v5e" in text and "dot.2" in text
        assert "mfu_ceiling" in text

    def test_compute_bound_on_slow_hbm(self):
        """Same program, a chip with proportionally slower HBM: a
        high-intensity dot flips compute-bound."""
        from deepspeed_tpu.analysis.roofline import analyze_hlo_text

        text = """\
HloModule big, num_partitions=1

ENTRY %m (a: f32[1024,1024], b: f32[1024,1024]) -> f32[1024,1024] {
  %a = f32[1024,1024]{1,0} parameter(0)
  %b = f32[1024,1024]{1,0} parameter(1)
  ROOT %dot = f32[1024,1024]{1,0} dot(f32[1024,1024]{1,0} %a, \
f32[1024,1024]{1,0} %b), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
"""
        rep = analyze_hlo_text(text, chip="cpu-sim")
        # intensity = 2*1024^3 / 12MB ≈ 170 fl/B > cpu-sim ridge (10)
        assert rep.regions[0].bound == "compute"
        assert rep.memory_bound_share() == 0.0

    def test_decode_mbu_ceiling_units(self):
        from deepspeed_tpu.analysis.roofline import decode_mbu_ceiling

        # pure bandwidth-bound step, zero overhead: ceiling is 1.0
        assert decode_mbu_ceiling(1e9, chip="v5e") == 1.0
        # uncredited overhead halves it
        assert abs(decode_mbu_ceiling(1e9, overhead_bytes=1e9,
                                      chip="v5e") - 0.5) < 1e-9
        # a compute-bound fat batch caps it below 1.0
        capped = decode_mbu_ceiling(1e6, flops=1e12, chip="v5e")
        assert 0.0 < capped < 1.0
        assert decode_mbu_ceiling(0.0, chip="v5e") == 0.0

    def test_summary_dict_shape(self):
        from deepspeed_tpu.analysis.roofline import analyze_hlo_text

        s = analyze_hlo_text(DOT_FUSION_TEXT, chip="v4").summary()
        assert s["chip"] == "v4" and s["regions"] == 3
        assert set(s) >= {"program", "predicted_step_us", "mfu_ceiling",
                          "total_flops", "total_bytes",
                          "memory_bound_share", "top_region"}
        assert "flops_vs_xla" not in s       # no live cross-check on text


# -------------------------------------------- the tier-1 gpt2 ZeRO-3 case
@pytest.fixture(scope="module")
def zero3_roofline():
    """ONE 8-dev ZeRO-3 engine under {perf, roofline}: the engine hook
    runs the pass after the first train_batch; everything later tests
    assert on is snapshotted HERE (the conftest autouse reset clears the
    program table after every test)."""
    _reset()
    engine, cfg = _mk_engine(extra={"perf": {}, "roofline": {}})
    batch = synthetic_lm_batch(8, 32, cfg.vocab_size, seed=0)
    engine.train_batch(batch)
    rep = engine._roofline_result
    entry = engine.perf_record("train_mfu", 0.05, "MFU")
    yield engine, rep, entry
    _reset()


@pytest.mark.analysis
@pytest.mark.perf
class TestRooflineZero3:
    def test_regex_flops_within_5pct_of_cost_analysis(self, zero3_roofline):
        """THE acceptance: on the sharded, optimizer-fused, mixed-
        precision train program the regex model and HloCostAnalysis
        count the same flops within 5%."""
        _, rep, _ = zero3_roofline
        assert rep is not None
        agree = rep.flops_agreement()
        assert agree is not None
        assert 0.95 <= agree <= 1.05, agree

    def test_report_names_top_memory_bound_fusion(self, zero3_roofline):
        _, rep, _ = zero3_roofline
        top = rep.top_memory_bound()
        assert top is not None and top.bound == "memory"
        assert top.name in rep.render(top_k=8)
        assert rep.num_partitions == 8
        assert 0.0 < rep.mfu_ceiling < 1.0
        assert rep.memory_bound_share() > 0.5   # tiny model: HBM-dominated

    def test_ledger_entry_hoists_ceiling_and_gap(self, zero3_roofline):
        """An MFU entry recorded under {perf, roofline} carries hoisted
        mfu_ceiling and mfu_gap (= ceiling − measured, clamped at 0) plus
        the attribution summary — what `ds_perf gate --metric mfu_gap`
        reads."""
        _, rep, entry = zero3_roofline
        assert entry["mfu_ceiling"] == round(rep.mfu_ceiling, 4)
        assert entry["mfu_gap"] == round(max(0.0, rep.mfu_ceiling - 0.05), 4)
        roof = entry["attribution"]["roofline"]
        assert roof["chip"] == "cpu-sim"
        assert roof["top_region"]["name"]
        assert roof["memory_bound_share"] > 0.5

    def test_gauges_for_ds_top(self, zero3_roofline):
        """The roofline/* gauges feed the ds_top / ds_metrics line."""
        from deepspeed_tpu.goodput.tail import render_roofline_line

        _, rep, _ = zero3_roofline
        gauges = {"roofline/mfu_ceiling": rep.mfu_ceiling,
                  "roofline/predicted_step_us": 1e6 * rep.predicted_step_s,
                  "roofline/memory_bound_share": rep.memory_bound_share(),
                  "goodput/mfu": 0.05}
        line = render_roofline_line(gauges, {})
        assert line and "mfu ceiling" in line and "memory-bound" in line
        assert render_roofline_line({"goodput/mfu": 0.05}, {}) is None


# ----------------------------------------------------------- mfu_gap gate
@pytest.mark.perf
class TestMfuGapGate:
    def _entry(self, gap, value=0.3):
        return {"metric": "m pretrain MFU (x)", "value": value,
                "unit": "MFU", "samples": [value] * 3,
                "fingerprint": "f", "headline": True,
                "mfu_ceiling": value + gap, "mfu_gap": gap,
                "attribution": {"mfu_ceiling": value + gap}}

    def test_compare_rider_floor_and_direction(self):
        from deepspeed_tpu.perf.ledger import compare

        r = compare(self._entry(0.05), self._entry(0.12))
        assert r["mfu_gap_regressed"] and r["mfu_gap_delta"] > 0
        # sub-floor growth (< 2 MFU points) is noise, not a regression
        assert not compare(self._entry(0.05),
                           self._entry(0.06))["mfu_gap_regressed"]
        # the improvement direction never flags
        assert not compare(self._entry(0.12),
                           self._entry(0.05))["mfu_gap_regressed"]
        # absent on either side: no verdict keys at all
        bare = self._entry(0.05)
        del bare["mfu_gap"]
        assert "mfu_gap_regressed" not in compare(bare, self._entry(0.05))

    def test_gate_exit2_on_synthetic_regression(self, tmp_path):
        from deepspeed_tpu.perf.cli import main as perf_main

        base = tmp_path / "base.jsonl"
        cand = tmp_path / "cand.jsonl"
        base.write_text(json.dumps(self._entry(0.05)) + "\n")
        cand.write_text(json.dumps(self._entry(0.12)) + "\n")
        rc = perf_main(["gate", "--baseline", str(base), "--candidate",
                        str(cand), "--metric", "mfu_gap"])
        assert rc == 2
        ok = perf_main(["gate", "--baseline", str(base), "--candidate",
                        str(base), "--metric", "mfu_gap"])
        assert ok == 0

    def test_gate_exit3_when_attribution_missing(self, tmp_path):
        from deepspeed_tpu.perf.cli import main as perf_main

        base = tmp_path / "base.jsonl"
        cand = tmp_path / "cand.jsonl"
        base.write_text(json.dumps(self._entry(0.05)) + "\n")
        bare = self._entry(0.05)
        del bare["mfu_gap"], bare["mfu_ceiling"], bare["attribution"]
        cand.write_text(json.dumps(bare) + "\n")
        rc = perf_main(["gate", "--baseline", str(base), "--candidate",
                        str(cand), "--metric", "mfu_gap"])
        assert rc == 3
        # --allow-missing downgrades to a warning
        ok = perf_main(["gate", "--baseline", str(base), "--candidate",
                        str(cand), "--metric", "mfu_gap",
                        "--allow-missing"])
        assert ok == 0


# ------------------------------------------------------------- CLI no-jax
@pytest.mark.analysis
class TestCliNoJax:
    def test_report_on_saved_dump_without_jax(self, tmp_path):
        """The ds_prof contract: a saved .hlo dump prices on a box with
        no jax (the bin/ script file-loads the stdlib modules)."""
        blocker = tmp_path / "nojax"
        blocker.mkdir()
        (blocker / "jax.py").write_text(
            "raise ImportError('no jax on this box')\n")
        dump = tmp_path / "step.hlo"
        dump.write_text(DOT_FUSION_TEXT)
        env = {**os.environ, "PYTHONPATH": str(blocker)}
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bin", "ds_roofline"),
             "report", "--hlo", str(dump), "--chip", "v5e"],
            capture_output=True, text=True, env=env)
        assert proc.returncode == 0, proc.stderr
        assert "roofline[" in proc.stdout and "dot.2" in proc.stdout

        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bin", "ds_roofline"),
             "report", "--hlo", str(dump), "--json"],
            capture_output=True, text=True, env=env)
        assert proc.returncode == 0, proc.stderr
        [rep] = json.loads(proc.stdout)
        assert rep["total_flops"] == 2 * 64 * 64 * 128 + 2 * 64 * 64
        assert rep["top_regions"][0]["name"] == "dot.2"

    def test_chips_subcommand_and_unknown_chip(self, tmp_path):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bin", "ds_roofline"),
             "chips"], capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr
        for chip in ("v4", "v5e", "v5p", "cpu-sim"):
            assert chip in proc.stdout
        dump = tmp_path / "s.hlo"
        dump.write_text(DOT_FUSION_TEXT)
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bin", "ds_roofline"),
             "report", "--hlo", str(dump), "--chip", "h100"],
            capture_output=True, text=True)
        assert proc.returncode == 2
        assert "v5e" in proc.stderr        # the known-chips hint


# ------------------------------------------------------------ config schema
@pytest.mark.analysis
class TestSchemaRoofline:
    BASE = {"train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "steps_per_print": 0}

    def test_unknown_chip_is_error(self):
        from deepspeed_tpu.analysis.schema import walk_config

        findings, _ = walk_config(
            {**self.BASE, "perf": {}, "roofline": {"chip": "h100"}},
            world_size=1)
        hits = [f for f in findings if f.severity == "error"
                and "roofline.chip" in f.citation]
        assert hits and "h100" in hits[0].message

    def test_roofline_without_perf_warns(self):
        from deepspeed_tpu.analysis.schema import walk_config

        findings, _ = walk_config({**self.BASE, "roofline": {}},
                                  world_size=1)
        assert any(f.severity == "warning" and f.citation == "roofline vs perf"
                   for f in findings)
        findings, _ = walk_config({**self.BASE, "perf": {},
                                   "roofline": {"chip": "v5e"}},
                                  world_size=1)
        assert not [f for f in findings if "roofline" in f.citation]

    def test_top_level_did_you_mean(self):
        from deepspeed_tpu.runtime.config import DeepSpeedConfig

        with pytest.raises(ValueError, match="roofline"):
            DeepSpeedConfig({**self.BASE, "rooflin": {}}, world_size=1)

    def test_block_typo_did_you_mean(self):
        from deepspeed_tpu.runtime.config import RooflineConfig

        with pytest.raises(ValueError, match="did you mean 'chip'"):
            RooflineConfig(chp="v5e")


# ------------------------------------------------------------ strict no-op
@pytest.mark.analysis
class TestStrictNoOp:
    def _without_modules(self):
        return {m: sys.modules.pop(m) for m in list(sys.modules)
                if m in (ROOF_MOD, CHIPS_MOD)}

    def test_block_absent_never_imports_module(self):
        saved = self._without_modules()
        try:
            _reset()
            engine, cfg = _mk_engine()
            engine.train_batch(synthetic_lm_batch(8, 32, cfg.vocab_size))
            assert not engine._roofline_done
            assert engine._roofline_result is None
            assert ROOF_MOD not in sys.modules
            assert CHIPS_MOD not in sys.modules
        finally:
            sys.modules.update(saved)
            _reset()

    def test_enabled_false_never_imports_module(self):
        saved = self._without_modules()
        try:
            _reset()
            engine, cfg = _mk_engine(extra={"roofline": {"enabled": False}})
            engine.train_batch(synthetic_lm_batch(8, 32, cfg.vocab_size))
            assert not engine._roofline_done
            assert ROOF_MOD not in sys.modules
        finally:
            sys.modules.update(saved)
            _reset()

    def test_perf_entry_without_block_has_no_roofline_keys(self):
        saved = self._without_modules()
        try:
            _reset()
            engine, cfg = _mk_engine(extra={"perf": {}})
            engine.train_batch(synthetic_lm_batch(8, 32, cfg.vocab_size))
            entry = engine.perf_record("train_mfu", 0.05, "MFU")
            assert "mfu_ceiling" not in entry
            assert "mfu_gap" not in entry
            assert "roofline" not in entry.get("attribution", {})
            assert ROOF_MOD not in sys.modules
        finally:
            sys.modules.update(saved)
            _reset()
