"""Hybrid engine (RLHF actor) tests — reference runtime/hybrid_engine.py role:
the same engine generates experience and trains on it, over shared weights."""

import numpy as np
import pytest

import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
from deepspeed_tpu.runtime.hybrid_engine import DeepSpeedHybridEngine

TINY = GPT2Config(vocab_size=256, n_positions=64, n_embd=32, n_layer=2, n_head=2,
                  remat=False, use_flash_attention=False)


def _make_engine(extra_cfg=None):
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 5e-3}},
        "bf16": {"enabled": True},
        "hybrid_engine": {"enabled": True, "max_out_tokens": 64},
        "steps_per_print": 0,
    }
    cfg.update(extra_cfg or {})
    engine, *_ = deepspeed_tpu.initialize(model=GPT2Model(TINY), config=cfg)
    return engine


def test_initialize_returns_hybrid_engine():
    engine = _make_engine()
    assert isinstance(engine, DeepSpeedHybridEngine)


def test_rlhf_smoke_generate_score_train():
    """The RLHF loop shape: generate -> score -> train step, twice."""
    engine = _make_engine()
    rng = np.random.RandomState(0)
    prompts = rng.randint(0, 256, size=(8, 8)).astype(np.int32)

    losses = []
    for it in range(2):
        engine.eval()
        seqs = np.asarray(engine.generate(prompts, max_new_tokens=8))
        assert seqs.shape == (8, 16)
        assert (seqs[:, :8] == prompts).all()
        # toy "reward model": mask loss onto the generated response tokens
        loss_mask = np.zeros_like(seqs, dtype=np.float32)
        loss_mask[:, 8:] = 1.0
        engine.train()
        loss = float(engine.train_batch(
            {"input_ids": seqs.astype(np.int32), "loss_mask": loss_mask}))
        assert np.isfinite(loss)
        losses.append(loss)
    stats = engine.hybrid_stats()
    assert stats["generate_calls"] == 2
    # the first (compile) call is excluded from steady-state token accounting
    assert stats["generated_tokens"] == 8 * 8


def test_generate_reflects_training_updates():
    """Weight sharing is live: after training, generation logits change."""
    engine = _make_engine()
    rng = np.random.RandomState(1)
    prompts = rng.randint(0, 256, size=(8, 8)).astype(np.int32)
    out0 = np.asarray(engine.generate(prompts, max_new_tokens=6, seed=7))
    batch = {"input_ids": rng.randint(0, 256, size=(8, 32)).astype(np.int32)}
    for _ in range(8):
        engine.train_batch(batch)
    out1 = np.asarray(engine.generate(prompts, max_new_tokens=6, seed=7))
    assert out0.shape == out1.shape
    assert not np.array_equal(out0, out1), \
        "generation ignored 8 optimizer steps — params not shared"


def test_generate_respects_max_out_tokens():
    engine = _make_engine()
    prompts = np.zeros((2, 60), np.int32)
    with pytest.raises(ValueError, match="max_out_tokens"):
        engine.generate(prompts, max_new_tokens=8)


def test_generate_needs_inference_protocol():
    from deepspeed_tpu.models.simple import SimpleModel

    engine, *_ = deepspeed_tpu.initialize(model=SimpleModel(hidden_dim=8, nlayers=2),
                                          config={
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "hybrid_engine": {"enabled": True},
        "steps_per_print": 0})
    with pytest.raises(NotImplementedError, match="inference protocol"):
        engine.generate(np.zeros((2, 4), np.int32))
