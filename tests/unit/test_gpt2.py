"""GPT-2 model tests: shapes, TP sharding, ZeRO-3 training on the faked mesh
(reference analogue: tests/model/Megatron_GPT2 sanity checks)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm import comm as dist
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model, synthetic_lm_batch

TINY = GPT2Config(vocab_size=512, n_positions=64, n_embd=64, n_layer=2, n_head=4,
                  dtype=jnp.float32, remat=False, use_flash_attention=False)


def test_forward_shapes():
    model = GPT2Model(TINY)
    params = model.init_params(jax.random.PRNGKey(0))
    ids = jnp.asarray(synthetic_lm_batch(2, 32, TINY.vocab_size)["input_ids"])
    logits = model.apply(params, ids)
    assert logits.shape == (2, 32, TINY.vocab_size)
    assert logits.dtype == jnp.float32


def test_causality():
    """Changing a future token must not change past logits."""
    model = GPT2Model(TINY)
    params = model.init_params(jax.random.PRNGKey(0))
    ids = np.asarray(synthetic_lm_batch(1, 16, TINY.vocab_size)["input_ids"])
    logits1 = model.apply(params, jnp.asarray(ids))
    ids2 = ids.copy()
    ids2[0, 10] = (ids2[0, 10] + 1) % TINY.vocab_size
    logits2 = model.apply(params, jnp.asarray(ids2))
    np.testing.assert_allclose(np.asarray(logits1[0, :10]), np.asarray(logits2[0, :10]),
                               rtol=1e-5, atol=1e-5)


def test_train_zero3_tp2():
    """End-to-end: GPT-2 tiny on a data=4 × tensor=2 mesh, ZeRO-3 + TP."""
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3, "weight_decay": 0.01}},
        "zero_optimization": {"stage": 3, "stage3_param_persistence_threshold": 0},
        "tpu": {"tensor": 2},
        "steps_per_print": 0,
        "gradient_clipping": 1.0,
    }
    model = GPT2Model(TINY)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg)
    batch = synthetic_lm_batch(8, 32, TINY.vocab_size, seed=3)
    losses = [float(engine.train_batch(batch)) for _ in range(6)]
    assert losses[-1] < losses[0], losses
    # qkv weight is column-parallel over tensor AND dp-sharded by zero-3
    qkv = engine.state.params["blocks"]["qkv_w"]
    assert np.prod(qkv.addressable_shards[0].data.shape) == qkv.size // 8


def test_remat_matches_no_remat():
    c1 = GPT2Config(**{**TINY.__dict__, "remat": True})
    model1, model2 = GPT2Model(c1), GPT2Model(TINY)
    params = model2.init_params(jax.random.PRNGKey(0))
    batch = {"input_ids": jnp.asarray(synthetic_lm_batch(2, 32, TINY.vocab_size)["input_ids"])}
    g1 = jax.grad(lambda p: model1.loss(p, batch))(params)
    g2 = jax.grad(lambda p: model2.loss(p, batch))(params)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_loss_mask():
    model = GPT2Model(TINY)
    params = model.init_params(jax.random.PRNGKey(0))
    ids = jnp.asarray(synthetic_lm_batch(2, 32, TINY.vocab_size)["input_ids"])
    full = model.loss(params, {"input_ids": ids})
    masked = model.loss(params, {"input_ids": ids,
                                 "loss_mask": jnp.ones_like(ids)})
    np.testing.assert_allclose(float(full), float(masked), rtol=1e-6)
