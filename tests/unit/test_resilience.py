"""Resilience subsystem tests — verified checkpoints with last-good
fallback, retried I/O, chaos injection, and the bad-step sentinel.

All CPU-only and deterministic: faults come from the seedable injector in
resilience/chaos.py (or direct on-disk corruption), never from timing. The
long randomized sweep (test_randomized_chaos_sweep) is listed in
tests/slow_tests.txt so tier-1 stays fast.
"""

import json
import os

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm import comm
from deepspeed_tpu.elasticity import DSElasticAgent
from deepspeed_tpu.models.simple import SimpleModel
from deepspeed_tpu.resilience import (BadStepError, BadStepSentinel, ChaosError, ChaosInjector, RestartBackoff,
                                      RetryPolicy, find_restorable_tag, install_chaos, retry, uninstall_chaos,
                                      verify_tag)
from deepspeed_tpu.resilience.manifest import candidate_tags
from deepspeed_tpu.runtime.config import DeepSpeedConfig

HIDDEN = 16


@pytest.fixture(autouse=True)
def _no_leftover_chaos():
    yield
    uninstall_chaos()


def _engine(resilience=None, async_save=False):
    comm.cdb = None
    cfg = {"train_batch_size": 8,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "tpu": {"data": 8},
           "checkpoint": {"async_save": async_save},
           "steps_per_print": 0}
    if resilience is not None:
        cfg["resilience"] = resilience
    engine, *_ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=HIDDEN, nlayers=2), config=cfg)
    return engine


def _batch(seed=0, bad=False):
    rng = np.random.RandomState(seed)
    x = rng.randn(8, HIDDEN).astype(np.float32)
    y = rng.randn(8, HIDDEN).astype(np.float32)
    if bad:
        x[0, 0] = np.nan
    return (x, y)


FAST_RETRY = {"max_attempts": 3, "base_delay": 0.001, "max_delay": 0.002,
              "deadline": 5.0}


# --------------------------------------------------------------- retry unit
class TestRetry:
    def test_succeeds_after_transient_failures(self):
        calls = {"n": 0}
        sleeps = []

        def fn():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("flaky fs")
            return "ok"

        out = retry(fn, RetryPolicy(max_attempts=5, base_delay=1.0, jitter=0.0,
                                    deadline=100.0),
                    sleep=sleeps.append, clock=lambda: 0.0)
        assert out == "ok"
        assert calls["n"] == 3
        assert sleeps == [1.0, 2.0]      # exponential, jitter disabled

    def test_gives_up_after_max_attempts(self):
        calls = {"n": 0}

        def fn():
            calls["n"] += 1
            raise OSError("always down")

        with pytest.raises(OSError, match="always down"):
            retry(fn, RetryPolicy(max_attempts=3, base_delay=0.0, deadline=None),
                  sleep=lambda d: None)
        assert calls["n"] == 3

    def test_gives_up_after_deadline(self):
        t = {"now": 0.0}
        calls = {"n": 0}

        def fn():
            calls["n"] += 1
            raise OSError("slow fs")

        # delays 1, 2, 4...: the 3rd attempt's sleep would cross the 5s
        # deadline, so exactly 3 calls happen even with 100 attempts allowed
        with pytest.raises(OSError, match="slow fs"):
            retry(fn, RetryPolicy(max_attempts=100, base_delay=1.0, multiplier=2.0,
                                  max_delay=100.0, jitter=0.0, deadline=5.0),
                  sleep=lambda d: t.__setitem__("now", t["now"] + d),
                  clock=lambda: t["now"])
        assert calls["n"] == 3

    def test_non_retryable_error_propagates_immediately(self):
        calls = {"n": 0}

        def fn():
            calls["n"] += 1
            raise ValueError("logic bug, not I/O")

        with pytest.raises(ValueError):
            retry(fn, RetryPolicy(max_attempts=5), sleep=lambda d: None)
        assert calls["n"] == 1

    def test_restart_backoff_grows_capped_and_resets(self):
        b = RestartBackoff(base_delay=0.1, multiplier=2.0, max_delay=1.0, jitter=0.0)
        assert [round(b.next_delay(), 3) for _ in range(5)] == [0.1, 0.2, 0.4, 0.8, 1.0]
        b.reset()
        assert round(b.next_delay(), 3) == 0.1


# ------------------------------------------------------------ sentinel unit
class TestSentinelUnit:
    def test_trips_after_patience_consecutive_bad(self):
        s = BadStepSentinel(patience=3)
        s.observe(1.0)                    # one clean step (ends scale warmup)
        assert not s.observe(float("nan"))
        assert not s.observe(1.0, overflow=True)
        assert s.observe(float("inf"))
        assert s.trips == 1

    def test_loss_scale_warmup_overflows_exempt(self):
        """A fresh fp16 run overflows for its first steps while the dynamic
        loss scale settles — that must never trip the sentinel; overflows
        AFTER the first clean step are real divergence signals."""
        s = BadStepSentinel(patience=2)
        for _ in range(10):
            assert not s.observe(1.0, overflow=True)
        assert s.trips == 0 and s.bad_streak == 0
        s.observe(1.0)                    # scale settled
        assert not s.observe(1.0, overflow=True)
        assert s.observe(1.0, overflow=True)
        assert s.trips == 1

    def test_good_step_resets_streak(self):
        s = BadStepSentinel(patience=2)
        assert not s.observe(float("nan"))
        assert not s.observe(0.5)                 # streak broken
        assert not s.observe(float("nan"))
        assert s.observe(float("nan"))

    def test_loss_spike_detection(self):
        s = BadStepSentinel(patience=2, spike_factor=10.0, window=8)
        for _ in range(4):
            assert not s.observe(1.0)
        assert not s.observe(50.0)                # spike 1
        assert s.observe(50.0)                    # spike 2 → trip
        assert "spike" in s.last_reason


# --------------------------------------------------------------- chaos unit
class TestChaos:
    def test_scripted_fail_at_is_exact(self):
        inj = ChaosInjector(fail_at={"latest": [2]})
        inj.before("latest", "p")                 # call 1: fine
        with pytest.raises(ChaosError):
            inj.before("latest", "p")             # call 2: injected
        inj.before("latest", "p")                 # call 3: fine again
        inj.before("client_state", "p")           # other ops untouched

    def test_seed_reproduces_fault_pattern(self):
        def pattern(seed):
            inj = ChaosInjector(seed=seed, failure_rate=0.5)
            out = []
            for _ in range(20):
                try:
                    inj.before("latest", "p")
                    out.append(0)
                except ChaosError:
                    out.append(1)
            return out

        assert pattern(7) == pattern(7)
        assert pattern(7) != pattern(8)

    def test_truncation_shortens_payload(self):
        inj = ChaosInjector(truncate_at={"client_state": [1]})
        inj.before("client_state", "p")
        data = b"x" * 100
        assert len(inj.corrupt("client_state", "p", data)) < 100
        inj.before("client_state", "p")
        assert inj.corrupt("client_state", "p", data) == data  # only call 1

    def test_env_spec_parsing(self):
        inj = ChaosInjector.from_env("seed=3,failure_rate=0.5,ops=latest+manifest")
        assert inj.seed == 3
        assert inj.failure_rate == 0.5
        assert inj.ops == {"latest", "manifest"}


# ------------------------------------------------------------ config block
def test_resilience_config_parses_and_rejects_unknown():
    c = DeepSpeedConfig({"train_batch_size": 8,
                         "resilience": {"verify_on_load": False,
                                        "retry": {"max_attempts": 2},
                                        "sentinel": {"enabled": True, "patience": 5}}})
    assert not c.resilience.verify_on_load
    assert c.resilience.retry.max_attempts == 2
    assert c.resilience.sentinel.patience == 5
    with pytest.raises(Exception):
        DeepSpeedConfig({"train_batch_size": 8, "resilience": {"bogus_knob": 1}})


# ----------------------------------------- restorable-tag detection (no engine)
def test_has_checkpoint_requires_restorable_tag(tmp_path):
    save = tmp_path / "ckpt"
    save.mkdir()
    agent = DSElasticAgent(lambda: None, str(save), install_signal_handlers=False)
    assert not agent._has_checkpoint()            # empty dir

    (save / "latest").write_text("global_step5")  # dangling pointer
    (save / "stray.txt").write_text("junk")
    assert not agent._has_checkpoint()            # non-empty but nothing loadable

    tag = save / "global_step5"
    tag.mkdir()
    (tag / "client_state.json").write_text("{}")
    assert not agent._has_checkpoint()            # half-written: state never committed

    st = tag / "state"
    st.mkdir()
    (st / "_CHECKPOINT_METADATA").write_text("{}")
    assert agent._has_checkpoint()                # committed (pre-manifest layout)

    # an explicit tag is a contract: another restorable tag existing must
    # not make the agent claim (and then fail/skip) a resume of THIS tag
    tagged = DSElasticAgent(lambda: None, str(save), tag="ckpt",
                            install_signal_handlers=False)
    assert not tagged._has_checkpoint()           # 'ckpt' itself isn't there


def _premanifest_orbax_tag(save, name):
    tag = save / name
    (tag / "state").mkdir(parents=True)
    (tag / "state" / "_CHECKPOINT_METADATA").write_text("{}")
    (tag / "client_state.json").write_text("{}")
    return tag


def test_premanifest_side_tag_does_not_outrank_latest(tmp_path):
    """Upgrade path: tags from before the manifest era carry no
    advance_latest intent, so a non-numeric side snapshot with a newer
    mtime must not beat the tag the 'latest' pointer names — only a tag
    with a provably greater step (crash-before-advance) outranks it."""
    save = tmp_path / "ckpt"
    save.mkdir()
    _premanifest_orbax_tag(save, "global_step100")
    (save / "latest").write_text("global_step100")
    _premanifest_orbax_tag(save, "best")          # newer mtime, no step
    assert candidate_tags(str(save))[0] == "global_step100"
    _premanifest_orbax_tag(save, "global_step101")  # newer committed work
    assert candidate_tags(str(save))[0] == "global_step101"


def test_non_orbax_layout_accepted(tmp_path):
    """ZeRO-Infinity-style snapshots (swap files + shared.npz, no orbax
    state/ tree) must still count as restorable for the elastic agent."""
    save = tmp_path / "ckpt"
    save.mkdir()
    tag = save / "global_step3"
    tag.mkdir()
    (tag / "client_state.json").write_text('{"global_steps": 3}')
    (tag / "shared.npz").write_bytes(b"\x93NUMPY")
    (tag / "layer_0.swp").write_bytes(b"\x00" * 8)
    ok, reason = verify_tag(str(tag))
    assert ok, reason
    agent = DSElasticAgent(lambda: None, str(save), install_signal_handlers=False)
    assert agent._has_checkpoint()


# --------------------------------------------- verified save/load round trips
@pytest.mark.chaos
class TestVerifiedCheckpoint:
    def test_save_writes_manifest_and_latest_last(self, tmp_path):
        engine = _engine()
        save = str(tmp_path / "ck")
        engine.train_batch(_batch())
        engine.save_checkpoint(save)
        tag_dir = os.path.join(save, "global_step1")
        ok, reason = verify_tag(tag_dir)
        assert ok, reason
        with open(os.path.join(tag_dir, "manifest.json")) as f:
            manifest = json.load(f)
        assert "client_state.json" in manifest["files"]
        assert any(k.startswith("state/") for k in manifest["state_files"])
        with open(os.path.join(save, "latest")) as f:
            assert f.read().strip() == "global_step1"

    def test_async_save_finalizes_manifest_after_commit(self, tmp_path):
        from deepspeed_tpu.runtime.checkpoint_engine.engine import \
            wait_for_pending_saves

        engine = _engine(async_save=True)
        save = str(tmp_path / "ck")
        engine.train_batch(_batch())
        engine.save_checkpoint(save)
        wait_for_pending_saves()
        ok, reason = verify_tag(os.path.join(save, "global_step1"))
        assert ok, reason
        assert find_restorable_tag(save) == "global_step1"

    def test_corrupt_sidecar_falls_back_to_previous_tag(self, tmp_path):
        engine = _engine()
        save = str(tmp_path / "ck")
        engine.train_batch(_batch())
        engine.save_checkpoint(save)              # global_step1, clean
        engine.train_batch(_batch(1))
        engine.save_checkpoint(save)              # global_step2
        # corrupt the newest tag's metadata on disk (bit-rot / torn write)
        meta = os.path.join(save, "global_step2", "client_state.json")
        with open(meta, "r+b") as f:
            f.truncate(max(1, os.path.getsize(meta) // 2))
        ok, reason = verify_tag(os.path.join(save, "global_step2"))
        assert not ok and "client_state.json" in reason
        path, _ = engine.load_checkpoint(save)
        assert path is not None and path.endswith("global_step1")
        assert int(engine.state.step) == 1

    def test_chaos_truncated_write_caught_at_load(self, tmp_path):
        engine = _engine(resilience={"retry": FAST_RETRY})
        save = str(tmp_path / "ck")
        engine.train_batch(_batch())
        engine.save_checkpoint(save)              # global_step1, clean
        engine.train_batch(_batch(1))
        # the 1st client_state write of the next save lands truncated — a
        # SILENT fault: the save itself reports success
        install_chaos(ChaosInjector(truncate_at={"client_state": [1]}))
        engine.save_checkpoint(save)              # global_step2, corrupt
        uninstall_chaos()
        assert find_restorable_tag(save) == "global_step1"
        path, _ = engine.load_checkpoint(save)
        assert path.endswith("global_step1")
        assert int(engine.state.step) == 1

    def test_crash_between_state_commit_and_latest_advance(self, tmp_path):
        engine = _engine(resilience={"retry": FAST_RETRY})
        save = str(tmp_path / "ck")
        engine.train_batch(_batch())
        engine.save_checkpoint(save)              # global_step1: latest → step1
        engine.train_batch(_batch(1))
        # every attempt at the 'latest' advance fails → save raises AFTER the
        # state committed and the manifest was written (the crash window)
        install_chaos(ChaosInjector(fail_at={"latest": range(1, 20)}))
        with pytest.raises(OSError):
            engine.save_checkpoint(save)
        uninstall_chaos()
        with open(os.path.join(save, "latest")) as f:
            assert f.read().strip() == "global_step1"   # pointer never moved
        # the newest tag still verifies and wins over the stale pointer: the
        # interrupted save costs nothing
        assert find_restorable_tag(save) == "global_step2"
        path, _ = engine.load_checkpoint(save)
        assert path.endswith("global_step2")
        assert int(engine.state.step) == 2

    def test_side_checkpoint_does_not_hijack_resume(self, tmp_path):
        engine = _engine()
        save = str(tmp_path / "ck")
        engine.train_batch(_batch())
        engine.save_checkpoint(save)              # global_step1, auto-resume tag
        engine.train_batch(_batch(1))
        # deliberate side save: newer, but must never win an automatic resume
        engine.save_checkpoint(save, tag="side_eval", save_latest=False)
        path, _ = engine.load_checkpoint(save)
        assert path.endswith("global_step1")
        path, _ = engine.load_checkpoint(save, tag="side_eval")
        assert path.endswith("side_eval")         # explicit request still honored
        assert int(engine.state.step) == 2
        # a side tag is NEVER an auto-resume candidate — not even as a last
        # resort once every auto-resume tag is gone (restoring a deliberate
        # side snapshot unasked would be silent wrong-weights substitution)
        import shutil
        shutil.rmtree(os.path.join(save, "global_step1"))
        assert candidate_tags(save) == []
        assert find_restorable_tag(save) is None
        path, _ = engine.load_checkpoint(save, tag="side_eval")
        assert path.endswith("side_eval")

    def test_named_latest_tag_wins_auto_resume(self, tmp_path):
        """A non-numeric tag named by the 'latest' pointer must not be
        demoted below older global_stepN tags just because no step parses
        from its name."""
        engine = _engine()
        save = str(tmp_path / "ck")
        engine.train_batch(_batch())
        engine.save_checkpoint(save)              # global_step1
        engine.train_batch(_batch(1))
        engine.save_checkpoint(save, tag="best")  # newest; latest → 'best'
        assert candidate_tags(save)[0] == "best"
        path, _ = engine.load_checkpoint(save)
        assert path.endswith("best")
        assert int(engine.state.step) == 2

    def test_resave_same_tag_drops_stale_manifest(self, tmp_path):
        """Re-saving to a fixed tag drops the previous save's manifest up
        front: a crash mid-overwrite must degrade to the pre-manifest
        acceptance, not fail verification against mixed generations."""
        engine = _engine(resilience={"retry": FAST_RETRY})
        save = str(tmp_path / "ck")
        engine.train_batch(_batch())
        engine.save_checkpoint(save, tag="ckpt")
        engine.train_batch(_batch(1))
        # the re-save writes the new client_state but dies at the manifest:
        # the OLD manifest would have hash-rejected the new client_state
        install_chaos(ChaosInjector(fail_at={"manifest": range(1, 20)}))
        with pytest.raises(OSError):
            engine.save_checkpoint(save, tag="ckpt")
        uninstall_chaos()
        tag_dir = os.path.join(save, "ckpt")
        assert not os.path.isfile(os.path.join(tag_dir, "manifest.json"))
        ok, reason = verify_tag(tag_dir)
        assert ok, reason                          # compat acceptance
        path, _ = engine.load_checkpoint(save, tag="ckpt")
        assert path is not None and path.endswith("ckpt")

    def test_chaos_failed_state_write_leaves_run_restorable(self, tmp_path):
        engine = _engine(resilience={"retry": FAST_RETRY})
        save = str(tmp_path / "ck")
        engine.train_batch(_batch())
        engine.save_checkpoint(save)              # global_step1, clean
        engine.train_batch(_batch(1))
        install_chaos(ChaosInjector(fail_at={"state_save": range(1, 20)}))
        with pytest.raises(OSError):
            engine.save_checkpoint(save)          # dies before any commit
        uninstall_chaos()
        assert find_restorable_tag(save) == "global_step1"
        path, _ = engine.load_checkpoint(save)
        assert path.endswith("global_step1")


# ------------------------------------------------------- bad-step sentinel
class TestSentinelInEngine:
    def test_rewinds_after_k_bad_steps(self, tmp_path):
        engine = _engine(resilience={"sentinel": {"enabled": True, "patience": 2,
                                                  "max_rewinds": 2}})
        save = str(tmp_path / "ck")
        engine.train_batch(_batch())
        engine.train_batch(_batch(1))
        engine.save_checkpoint(save)
        assert int(engine.state.step) == 2
        engine.train_batch(_batch(2, bad=True))   # streak 1 (step skipped, counter advances)
        engine.train_batch(_batch(3, bad=True))   # streak 2 → rewind
        assert int(engine.state.step) == 2        # back at the checkpoint
        assert engine._sentinel_rewinds == 1
        loss = engine.train_batch(_batch(4))      # training continues cleanly
        assert np.isfinite(float(loss))
        assert int(engine.state.step) == 3

    def test_raises_without_any_checkpoint(self):
        engine = _engine(resilience={"sentinel": {"enabled": True, "patience": 1}})
        with pytest.raises(BadStepError, match="nothing to rewind"):
            engine.train_batch(_batch(bad=True))

    def test_gives_up_after_max_rewinds(self, tmp_path):
        engine = _engine(resilience={"sentinel": {"enabled": True, "patience": 1,
                                                  "max_rewinds": 1}})
        save = str(tmp_path / "ck")
        engine.train_batch(_batch())
        engine.save_checkpoint(save)
        engine.train_batch(_batch(1, bad=True))   # trip 1 → rewind
        assert engine._sentinel_rewinds == 1
        with pytest.raises(BadStepError, match="giving up"):
            engine.train_batch(_batch(2, bad=True))   # trip 2 → budget spent


# ------------------------------------------------- elastic agent integration
def test_agent_surfaces_restart_reasons(tmp_path):
    attempts = {"n": 0}

    def flaky_batches():
        attempts["n"] += 1
        first = attempts["n"] == 1
        for i in range(1000):
            if first and i == 2:
                raise RuntimeError("injected step failure")
            yield _batch(i % 4)

    def factory():
        return _engine()

    agent = DSElasticAgent(factory, str(tmp_path / "ckpt"),
                           checkpoint_interval=1, max_restarts=2,
                           install_signal_handlers=False,
                           restart_backoff=RestartBackoff(base_delay=0.0, jitter=0.0))
    out = agent.run(flaky_batches, num_steps=4)
    assert out["status"] == "complete"
    assert out["restarts"] == 1
    assert len(out["restart_reasons"]) == 1
    assert "injected step failure" in out["restart_reasons"][0]
    assert out["restart_log"][0]["restart"] == 1
    assert out["restart_log"][0]["backoff_s"] == 0.0
    # a healthy checkpoint interval after the restart ends the incident:
    # the escalated delay must not carry over to the next unrelated failure
    assert agent.restart_backoff.attempt == 0


def test_agent_accounts_for_sentinel_rewind(tmp_path):
    """A sentinel rewind inside train_batch moves the engine's step counter
    backwards; the agent must follow it and keep training until num_steps
    are ACTUALLY trained, not until its own batch count runs out."""
    def batches():
        yield _batch(0)
        yield _batch(1)
        yield _batch(2, bad=True)        # nan loss → sentinel trips → rewind
        for i in range(100):
            yield _batch(3 + i)

    def factory():
        return _engine(resilience={"sentinel": {"enabled": True, "patience": 1,
                                                "max_rewinds": 2}})

    agent = DSElasticAgent(factory, str(tmp_path / "ckpt"),
                           checkpoint_interval=1, max_restarts=0,
                           install_signal_handlers=False)
    out = agent.run(batches, num_steps=4)
    assert out["status"] == "complete"
    assert out["final_step"] == 4        # rewound step was re-trained
    assert agent.engine._sentinel_rewinds == 1


# ---------------------------------------------------- randomized chaos sweep
@pytest.mark.chaos
def test_randomized_chaos_sweep(tmp_path):
    """Game-day: random write failures/truncations/delays across repeated
    saves must NEVER leave the run unrestorable — load always lands on a tag
    that verifies. Long; listed in tests/slow_tests.txt (tier-2)."""
    engine = _engine(resilience={"retry": {"max_attempts": 2, "base_delay": 0.001,
                                           "max_delay": 0.002, "deadline": 2.0}})
    for seed in range(6):
        save = str(tmp_path / f"sweep{seed}")
        engine.train_batch(_batch(seed))
        engine.save_checkpoint(save)              # clean baseline tag
        install_chaos(ChaosInjector(seed=seed, failure_rate=0.15,
                                    truncate_rate=0.25, delay_rate=0.1,
                                    max_delay_s=0.005))
        for i in range(3):
            engine.train_batch(_batch(seed * 10 + i))
            try:
                engine.save_checkpoint(save)
            except OSError:
                pass                              # an injected unrecoverable fault
        uninstall_chaos()
        tag = find_restorable_tag(save)
        assert tag is not None, f"seed {seed}: no restorable tag in {candidate_tags(save)}"
        path, _ = engine.load_checkpoint(save)
        assert path is not None and path.endswith(tag), \
            f"seed {seed}: loaded {path}, expected tag {tag}"
