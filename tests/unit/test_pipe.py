"""Pipeline tests: schedule order (reference test_pipe_schedule.py), module
partitioning, and end-to-end pipelined training vs the non-pipelined model."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model, synthetic_lm_batch
from deepspeed_tpu.models.gpt2_pipe import PipelinedGPT2
from deepspeed_tpu.runtime.pipe.module import LayerSpec, PipelineModule, partition_balanced
from deepspeed_tpu.runtime.pipe.schedule import (BackwardPass, ForwardPass, InferenceSchedule,
                                                 LoadMicroBatch, OptimizerStep, RecvActivation,
                                                 RecvGrad, SendActivation, SendGrad, TrainSchedule)

TINY = GPT2Config(vocab_size=512, n_positions=64, n_embd=64, n_layer=4, n_head=4,
                  dtype=jnp.float32, remat=False, use_flash_attention=False)


# ------------------------------------------------------------------- schedule
def test_inference_schedule_order():
    sched = InferenceSchedule(micro_batches=4, stages=2, stage_id=0)
    steps = list(sched.steps())
    assert len(steps) == 5
    assert any(isinstance(c, LoadMicroBatch) for c in steps[0])
    assert any(isinstance(c, ForwardPass) for c in steps[0])
    assert any(isinstance(c, SendActivation) for c in steps[0])


def test_train_schedule_1f1b_properties():
    """Every microbatch gets exactly one Forward and one Backward, sends and
    recvs pair up across neighboring stages."""
    mb, stages = 4, 2
    for stage in range(stages):
        sched = TrainSchedule(micro_batches=mb, stages=stages, stage_id=stage)
        fwd = [c.buffer_id for step in sched for c in step if isinstance(c, ForwardPass)]
        bwd = [c.buffer_id for step in sched for c in step if isinstance(c, BackwardPass)]
        assert sorted(fwd) == list(range(mb))
        assert sorted(bwd) == list(range(mb))
        opt = [c for step in sched for c in step if isinstance(c, OptimizerStep)]
        assert len(opt) == 1
    s0 = TrainSchedule(micro_batches=mb, stages=stages, stage_id=0)
    s1 = TrainSchedule(micro_batches=mb, stages=stages, stage_id=1)
    sends0 = sum(isinstance(c, SendActivation) for step in s0 for c in step)
    recvs1 = sum(isinstance(c, RecvActivation) for step in s1 for c in step)
    assert sends0 == recvs1 == mb
    gsends1 = sum(isinstance(c, SendGrad) for step in s1 for c in step)
    grecvs0 = sum(isinstance(c, RecvGrad) for step in s0 for c in step)
    assert gsends1 == grecvs0 == mb


def test_backward_follows_forward_per_stage():
    sched = TrainSchedule(micro_batches=4, stages=2, stage_id=1)
    seen_fwd = set()
    for step in sched:
        for cmd in step:
            if isinstance(cmd, ForwardPass):
                seen_fwd.add(cmd.buffer_id)
            if isinstance(cmd, BackwardPass):
                assert cmd.buffer_id in seen_fwd


# --------------------------------------------------------------- partitioning
def test_partition_balanced():
    assert partition_balanced([1, 1, 1, 1], 2) == [0, 2, 4]
    parts = partition_balanced([4, 1, 1, 1, 1], 2)
    assert parts[0] == 0 and parts[-1] == 5
    # heavy first layer should sit alone-ish
    assert parts[1] <= 2


class _Dummy:
    def __init__(self, n=10):
        self._n = n

    def num_params(self):
        return self._n


def test_pipeline_module_partition():
    layers = [LayerSpec(_Dummy, 100)] + [LayerSpec(_Dummy, 10) for _ in range(6)]
    pm = PipelineModule(layers=layers, num_stages=2, partition_method="parameters")
    assert pm.parts[0] == 0 and pm.parts[-1] == 7
    assert pm.stage_owner(0) == 0
    assert pm.stage_owner(6) == 1
    pm_u = PipelineModule(layers=layers, num_stages=2, partition_method="uniform")
    assert pm_u.parts == [0, 4, 7] or pm_u.parts == [0, 3, 7]


# ------------------------------------------------------------------ end-to-end
def _mk_engine(model, pp, extra=None, model_parameters=None):
    from deepspeed_tpu.comm import comm

    comm.cdb = None
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "tpu": {"pipe": pp},
        "steps_per_print": 0,
    }
    cfg.update(extra or {})
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg,
                                               model_parameters=model_parameters)
    return engine


def test_pipelined_matches_plain():
    """pp=2 pipelined loss must match the plain model numerically."""
    batch = synthetic_lm_batch(8, 32, TINY.vocab_size, seed=5)
    plain = _mk_engine(GPT2Model(TINY), pp=1)
    piped = _mk_engine(PipelinedGPT2(TINY, num_stages=2, num_micro=4), pp=2)
    l_plain = [float(plain.train_batch(batch)) for _ in range(4)]
    l_pipe = [float(piped.train_batch(batch)) for _ in range(4)]
    np.testing.assert_allclose(l_plain, l_pipe, rtol=5e-4, atol=5e-5)


VARIANTS = {
    # the BASELINE "GPT-NeoX 6.7B ZeRO-3 + pipeline" config's switches
    "neox": dict(rotary_pct=0.25, parallel_residual=True),
    "bloom": dict(alibi=True, embed_layernorm=True),
    "gptj": dict(rotary_pct=0.5, rotary_interleaved=True, parallel_residual=True,
                 tie_embeddings=False, lm_head_bias=True),
}


@pytest.mark.parametrize("variant", sorted(VARIANTS))
def test_pipelined_variant_matches_plain(variant):
    """The variant families must pipeline: pp=2 1F1B loss == plain loss for
    the NeoX/BLOOM/GPT-J switch sets (reference pipe/module.py:353 runs
    arbitrary stage content; here the switches thread through _stage_fn)."""
    cfg = dataclasses.replace(TINY, **VARIANTS[variant])
    batch = synthetic_lm_batch(8, 32, cfg.vocab_size, seed=7)
    plain = _mk_engine(GPT2Model(cfg), pp=1)
    piped = _mk_engine(PipelinedGPT2(cfg, num_stages=2, num_micro=4), pp=2)
    l_plain = [float(plain.train_batch(batch)) for _ in range(3)]
    l_pipe = [float(piped.train_batch(batch)) for _ in range(3)]
    np.testing.assert_allclose(l_plain, l_pipe, rtol=5e-4, atol=5e-5)


def test_pipelined_llama_gqa_matches_plain():
    """LLaMA (GQA + RoPE + SwiGLU) through the 1F1B executor: pp=2 loss ==
    plain loss — the GQA leg of the variant-pipelining matrix."""
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel
    from deepspeed_tpu.models.llama_pipe import PipelinedLlama

    cfg = LlamaConfig(vocab_size=512, n_positions=64, n_embd=64, n_layer=4,
                      n_head=4, n_kv_head=2, dtype=jnp.float32, remat=False,
                      use_flash_attention=False)
    batch = synthetic_lm_batch(8, 32, cfg.vocab_size, seed=9)
    plain = _mk_engine(LlamaModel(cfg), pp=1)
    piped = _mk_engine(PipelinedLlama(cfg, num_stages=2, num_micro=4), pp=2)
    l_plain = [float(plain.train_batch(batch)) for _ in range(3)]
    l_pipe = [float(piped.train_batch(batch)) for _ in range(3)]
    np.testing.assert_allclose(l_plain, l_pipe, rtol=5e-4, atol=5e-5)


def test_pipeline_with_zero1():
    batch = synthetic_lm_batch(8, 32, TINY.vocab_size, seed=5)
    piped = _mk_engine(PipelinedGPT2(TINY, num_stages=2, num_micro=2), pp=2,
                       extra={"zero_optimization": {"stage": 1}})
    losses = [float(piped.train_batch(batch)) for _ in range(5)]
    assert losses[-1] < losses[0]


def test_pipeline_4stages_with_tp():
    from deepspeed_tpu.comm import comm

    batch = synthetic_lm_batch(8, 32, TINY.vocab_size, seed=5)
    piped = _mk_engine(PipelinedGPT2(TINY, num_stages=4, num_micro=4), pp=4,
                       extra={"tpu": {"pipe": 4, "tensor": 2}})
    losses = [float(piped.train_batch(batch)) for _ in range(4)]
    assert losses[-1] < losses[0]
    # stage params sharded over pipe axis
    qkv = piped.state.params["stages"]["qkv_w"]
    assert qkv.shape[0] == 4


# ---------------------------------------------------------------- 1F1B
def test_1f1b_matches_gpipe_and_plain():
    """pp=4 1F1B: loss AND training trajectory match GPipe and the plain
    model (hand-written backward must equal AD's)."""
    batch = synthetic_lm_batch(8, 32, TINY.vocab_size, seed=7)
    plain = _mk_engine(GPT2Model(TINY), pp=1)
    gpipe = _mk_engine(PipelinedGPT2(TINY, num_stages=4, num_micro=8,
                                     schedule="gpipe"), pp=4)
    f1b = _mk_engine(PipelinedGPT2(TINY, num_stages=4, num_micro=8,
                                   schedule="1f1b"), pp=4)
    l_plain = [float(plain.train_batch(batch)) for _ in range(4)]
    l_gpipe = [float(gpipe.train_batch(batch)) for _ in range(4)]
    l_f1b = [float(f1b.train_batch(batch)) for _ in range(4)]
    np.testing.assert_allclose(l_f1b, l_gpipe, rtol=5e-4, atol=5e-5)
    np.testing.assert_allclose(l_f1b, l_plain, rtol=5e-4, atol=5e-5)


def test_1f1b_bounded_activation_memory():
    """The point of 1F1B: temp memory stays O(stages), not O(microbatches).
    Compare compiled temp sizes of the grad programs at M=16 vs M=4: GPipe
    grows roughly linearly with M; 1F1B must grow far slower."""
    from deepspeed_tpu.comm import comm
    from deepspeed_tpu.runtime.pipe.engine import (pipelined_loss_fn,
                                                   pipelined_loss_fn_1f1b)
    from deepspeed_tpu.parallel.topology import build_mesh

    comm.cdb = None
    mesh = build_mesh(axis_dims={"pipe": 4, "data": 2, "expert": 1,
                                 "seq": 1, "tensor": 1})
    comm.init_distributed(mesh=mesh, verbose=False)

    model = PipelinedGPT2(TINY, num_stages=4, num_micro=4)
    params = model.init_params(jax.random.PRNGKey(0))

    def temp_bytes(builder, M, batch_rows):
        m = PipelinedGPT2(TINY, num_stages=4, num_micro=M)
        loss = builder(stage_fn=m._stage_fn, first_stage_fn=m._first_stage_fn,
                       last_stage_loss_fn=m._last_stage_loss_fn,
                       num_micro=M, mesh=mesh, remat_stage=True)
        batch = synthetic_lm_batch(batch_rows, 32, TINY.vocab_size)
        ids = jnp.asarray(batch["input_ids"])
        with mesh:
            g = jax.jit(jax.grad(lambda p, b: loss(p, b, None)))
            compiled = g.lower(params, ids).compile()
        return compiled.memory_analysis().temp_size_in_bytes

    # per-microbatch size constant (rows = 2*M), so more microbatches =
    # same global tokens per microbatch count difference isolated
    gp_small = temp_bytes(pipelined_loss_fn, 4, 16)
    gp_big = temp_bytes(pipelined_loss_fn, 16, 64)
    f_small = temp_bytes(pipelined_loss_fn_1f1b, 4, 16)
    f_big = temp_bytes(pipelined_loss_fn_1f1b, 16, 64)
    gp_growth = gp_big / gp_small
    f_growth = f_big / f_small
    # GPipe stacks per-tick carries: ~4x when M goes 4->16. 1F1B holds a
    # fixed ring buffer: growth must be decisively smaller.
    assert f_growth < 0.6 * gp_growth, (gp_growth, f_growth)


def test_1f1b_with_tp_and_zero():
    """1F1B composes with tensor parallelism + ZeRO-1 (auto axes)."""
    batch = synthetic_lm_batch(8, 32, TINY.vocab_size, seed=9)
    engine = _mk_engine(PipelinedGPT2(TINY, num_stages=2, num_micro=4),
                        pp=2, extra={"tpu": {"pipe": 2, "tensor": 2},
                                     "zero_optimization": {"stage": 1}})
    losses = [float(engine.train_batch(batch)) for _ in range(5)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_1f1b_bf16_default_dtype():
    """The default GPT2Config dtype is bfloat16 — the 1F1B carry must ride
    the activation dtype (regression: fp32 g_recv init broke the scan)."""
    cfg = GPT2Config(vocab_size=256, n_positions=64, n_embd=32, n_layer=4,
                     n_head=2, remat=False, use_flash_attention=False)
    batch = synthetic_lm_batch(8, 32, cfg.vocab_size, seed=11)
    engine = _mk_engine(PipelinedGPT2(cfg, num_stages=4, num_micro=4), pp=4,
                        extra={"bf16": {"enabled": True}})
    losses = [float(engine.train_batch(batch)) for _ in range(4)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]
    # eval path (forward-only primal) agrees with training loss scale
    ev = float(engine.eval_batch(batch))
    assert np.isfinite(ev)


def test_1f1b_clock_satisfies_schedule_invariants():
    """The in-jit eager 1F1B clock must satisfy the same dependency
    invariants as the tested TrainSchedule: every microbatch forwarded
    exactly once and backwarded exactly once per stage, bwd after fwd,
    producer tick + 1 = consumer tick for activations AND grads, and
    in-flight activations bounded by O(S) independent of M."""
    for S, M in ((2, 4), (4, 8), (4, 32)):
        T = M + 2 * S - 2
        for s in range(S):
            fwd_ticks = {}
            bwd_ticks = {}
            in_flight, peak = 0, 0
            for t in range(T):
                f = t - s
                if 0 <= f < M:
                    fwd_ticks[f] = t
                    in_flight += 1
                b = t - (2 * S - 2 - s)
                if 0 <= b < M:
                    bwd_ticks[b] = t
                    in_flight -= 1
                peak = max(peak, in_flight)
            assert sorted(fwd_ticks) == list(range(M))
            assert sorted(bwd_ticks) == list(range(M))
            for m in range(M):
                assert bwd_ticks[m] >= fwd_ticks[m]          # bwd after fwd
            # activation alignment: stage s produces fwd m at fwd_ticks[m];
            # stage s+1 consumes it at its own fwd tick = m + (s+1)
            if s + 1 < S:
                for m in range(M):
                    assert fwd_ticks[m] + 1 == m + (s + 1)
            # grad alignment: stage s emits grad of m at bwd tick; stage s-1
            # consumes at m + (2S-2-(s-1))
            if s > 0:
                for m in range(M):
                    assert bwd_ticks[m] + 1 == m + (2 * S - 2 - (s - 1))
            # 1F1B memory bound: independent of M, matches the ring buffer
            assert peak <= 2 * (S - 1 - s) + 1 <= 2 * S


def test_universal_checkpoint_across_pipeline_degree():
    """Reference universal_checkpoint.py role for pp changes: a pp=1 run's
    checkpoint resumes on a pp=2 mesh (structure conversion + the checkpoint
    engine's reshard-on-load), and keeps training."""
    batch = synthetic_lm_batch(8, 32, TINY.vocab_size, seed=13)
    flat_engine = _mk_engine(GPT2Model(TINY), pp=1)
    for _ in range(3):
        flat_engine.train_batch(batch)
    l_flat = float(flat_engine.eval_batch(batch))
    flat_params = jax.tree.map(np.asarray, flat_engine.state.params)

    # structure-convert and boot a pp=2 engine from the converted params
    pipe_params = PipelinedGPT2.flat_to_pipe(flat_params, num_stages=2)
    pipe_engine = _mk_engine(PipelinedGPT2(TINY, num_stages=2, num_micro=4),
                             pp=2, model_parameters=pipe_params)
    l_pipe = float(pipe_engine.eval_batch(batch))
    np.testing.assert_allclose(l_pipe, l_flat, rtol=5e-3, atol=5e-4)
    # and training continues from the restored weights
    l_next = float(pipe_engine.train_batch(batch))
    assert np.isfinite(l_next)

    # round trip the TRAINED pipe-engine state back to flat: every leaf of
    # the blocks subtree and the shared subtree must survive bit-exact
    trained_pipe = jax.tree.map(np.asarray, pipe_engine.state.params)
    back = PipelinedGPT2.pipe_to_flat(trained_pipe)
    again = PipelinedGPT2.flat_to_pipe(back, num_stages=2)
    flat_b, flat_t = jax.tree_util.tree_flatten_with_path(again)[0], \
        jax.tree_util.tree_flatten_with_path(trained_pipe)[0]
    assert [p for p, _ in flat_b] == [p for p, _ in flat_t]
    for (path, a), (_, b) in zip(flat_b, flat_t):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=str(path))
    # and the flat tree matches the original model's structure
    assert set(back) == set(flat_params)
    assert back["blocks"]["qkv_w"].shape == flat_params["blocks"]["qkv_w"].shape
