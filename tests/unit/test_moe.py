"""MoE tests (reference: tests/unit/moe/test_moe.py + gating unit tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, synthetic_lm_batch
from deepspeed_tpu.models.gpt2_moe import MoEGPT2
from deepspeed_tpu.moe.layer import MoE
from deepspeed_tpu.moe.sharded_moe import _capacity, top1gating, top2gating
from deepspeed_tpu.utils.groups import _get_expert_parallel_ranks


# ------------------------------------------------------------------ gating
def test_capacity_math():
    assert _capacity(64, 8, 1.0, 4) == 8
    assert _capacity(64, 8, 1.5, 4) == 12
    assert _capacity(8, 8, 1.0, 4) == 4  # min_capacity floor


def test_top1_dispatch_shapes_and_conservation():
    rng = jax.random.PRNGKey(0)
    logits = jax.random.normal(rng, (32, 4))
    l_aux, combine, dispatch, cap = top1gating(logits, capacity_factor=2.0)
    assert combine.shape == (32, 4, cap) and dispatch.shape == (32, 4, cap)
    # each kept token dispatched exactly once, gates in (0,1]
    per_token = np.asarray(jnp.sum(dispatch, axis=(1, 2)))
    assert set(per_token.tolist()) <= {0.0, 1.0}
    assert float(l_aux) > 0
    # every expert queue slot used at most once
    per_slot = np.asarray(jnp.sum(dispatch, axis=0))
    assert per_slot.max() <= 1.0


def test_top1_capacity_drops_overflow():
    # all tokens want expert 0 → only `cap` survive
    logits = jnp.zeros((16, 4)).at[:, 0].set(10.0)
    l_aux, combine, dispatch, cap = top1gating(logits, capacity_factor=1.0, min_capacity=2)
    kept = float(jnp.sum(dispatch))
    assert kept == cap


def test_top2_two_experts_per_token():
    rng = jax.random.PRNGKey(1)
    logits = jax.random.normal(rng, (32, 8))
    l_aux, combine, dispatch, cap = top2gating(logits, capacity_factor=2.0)
    per_token = np.asarray(jnp.sum(dispatch, axis=(1, 2)))
    assert per_token.max() <= 2.0
    # combine weights of each token sum to ~1 (renormalized)
    sums = np.asarray(jnp.sum(combine, axis=(1, 2)))
    kept = per_token == 2.0
    np.testing.assert_allclose(sums[kept], 1.0, rtol=1e-5)


# -------------------------------------------------------------- group math
def test_expert_parallel_ranks():
    ep, edp = _get_expert_parallel_ranks(world_size=16, model_parallel_size=2,
                                         expert_parallel_size=4)
    assert [0, 2, 4, 6] in ep and [8, 10, 12, 14] in ep
    assert [1, 3, 5, 7] in ep and [9, 11, 13, 15] in ep
    assert [0, 8] in edp and [6, 14] in edp and [1, 9] in edp


# ---------------------------------------------------------------- MoE layer
def test_moe_layer_forward_backward():
    moe = MoE(hidden_size=16, num_experts=4, k=1, capacity_factor=2.0)
    params = moe.init_params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16))

    def loss(p):
        out, aux = moe(p, x, train=True)
        return jnp.sum(out ** 2) + 0.01 * aux

    g = jax.grad(loss)(params)
    assert all(np.all(np.isfinite(np.asarray(l))) for l in jax.tree.leaves(g))
    # gate gets gradient (through combine weights)
    assert float(jnp.max(jnp.abs(g["gate"]["wg"]))) > 0


def test_residual_moe():
    moe = MoE(hidden_size=16, num_experts=2, use_residual=True)
    params = moe.init_params(jax.random.PRNGKey(0))
    out, aux = moe(params, jax.random.normal(jax.random.PRNGKey(1), (4, 16)))
    assert out.shape == (4, 16)


# ------------------------------------------------------------------ end2end
def test_moe_gpt2_trains_with_expert_parallel():
    """Switch-8-experts over a 4-way expert axis (BASELINE milestone config)."""
    from deepspeed_tpu.comm import comm

    comm.cdb = None
    cfg = GPT2Config(vocab_size=512, n_positions=64, n_embd=64, n_layer=2, n_head=4,
                     dtype=jnp.float32, remat=False, use_flash_attention=False)
    model = MoEGPT2(cfg, num_experts=8, ep_size=4)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "tpu": {"expert": 4},
        "steps_per_print": 0,
    })
    batch = synthetic_lm_batch(8, 32, cfg.vocab_size, seed=7)
    losses = [float(engine.train_batch(batch)) for _ in range(6)]
    assert losses[-1] < losses[0], losses
    # expert weights actually sharded over the expert axis
    wi = engine.state.params["moe"]["experts"]["wi"]  # (n_moe, E, D, H)
    shard = wi.addressable_shards[0].data.shape
    assert shard[1] == wi.shape[1] // 4


def test_top1_no_drop_keeps_all_tokens():
    """drop_tokens=False: capacity grows to fit every routed token
    (reference top1gating drop_tokens=False branch)."""
    # adversarial logits: every token wants expert 0
    logits = jnp.concatenate([jnp.full((32, 1), 5.0), jnp.zeros((32, 3))], axis=1)
    l_aux, combine, dispatch, _ = top1gating(logits, capacity_factor=1.0,
                                             min_capacity=1, drop_tokens=False)
    # all 32 tokens dispatched (nothing dropped despite capacity_factor=1)
    assert float(dispatch.sum()) == 32.0


def test_top1_capacity_factor_scales_drops():
    """Bigger capacity_factor keeps more overflow tokens."""
    logits = jnp.concatenate([jnp.full((32, 1), 5.0), jnp.zeros((32, 3))], axis=1)
    kept = {}
    for cf in (1.0, 2.0, 4.0):
        _, _, dispatch, _ = top1gating(logits, capacity_factor=cf,
                                       min_capacity=1, drop_tokens=True)
        kept[cf] = float(dispatch.sum())
    assert kept[1.0] < kept[2.0] < kept[4.0]
    assert kept[4.0] <= 32.0


# ------------------------------------------------------- serving (EP inference)
def test_moe_prefill_decode_matches_full_forward():
    """Incremental MoE decode must reproduce teacher-forced logits.
    drop_tokens=False: capacity dropping is a function of the flattened token
    population, which differs between prefill and the full forward, so only
    the no-drop configuration is exactly causal."""
    cfg = GPT2Config(vocab_size=512, n_positions=128, n_embd=64, n_layer=4,
                     n_head=4, dtype=jnp.float32, remat=False,
                     use_flash_attention=False)
    model = MoEGPT2(cfg, num_experts=4, ep_size=1, drop_tokens=False)
    params = model.init_params(jax.random.PRNGKey(0))
    ids = jnp.asarray(synthetic_lm_batch(2, 16, cfg.vocab_size)["input_ids"])

    full_logits = model.apply(params, ids)  # (B, T, V)

    cache = model.init_cache(2, 32)
    logits_p, cache = model.prefill(params, ids[:, :8], cache)
    np.testing.assert_allclose(np.asarray(logits_p),
                               np.asarray(full_logits[:, 7]),
                               rtol=1e-4, atol=1e-4)
    for t in range(8, 16):
        logits_d, cache = model.decode_step(params, ids[:, t], cache)
        np.testing.assert_allclose(np.asarray(logits_d),
                                   np.asarray(full_logits[:, t]),
                                   rtol=1e-4, atol=1e-4)


def test_moe_inference_ep4_matches_ep1():
    """Expert-parallel generate (reference inference/config.py moe block +
    containers/base_moe.py): a TRAINED 8-expert model served over an
    expert=4 mesh must produce the same tokens as ep=1."""
    from deepspeed_tpu.comm import comm

    comm.cdb = None
    cfg = GPT2Config(vocab_size=512, n_positions=128, n_embd=64, n_layer=2,
                     n_head=4, dtype=jnp.float32, remat=False,
                     use_flash_attention=False)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=MoEGPT2(cfg, num_experts=8, ep_size=4),
        config={"train_batch_size": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "tpu": {"expert": 4}, "steps_per_print": 0})
    batch = synthetic_lm_batch(8, 32, cfg.vocab_size, seed=7)
    for _ in range(3):
        loss = engine.train_batch(batch)
    assert np.isfinite(float(loss))
    trained = engine.module_state_dict()

    prompt = np.asarray(synthetic_lm_batch(2, 8, cfg.vocab_size,
                                           seed=9)["input_ids"])
    comm.cdb = None
    e1 = deepspeed_tpu.init_inference(
        MoEGPT2(cfg, num_experts=8, ep_size=1),
        config={"dtype": "float32", "max_out_tokens": 128}, params=trained)
    assert e1.ep_world_size == 1
    out1 = np.asarray(e1.generate(prompt, max_new_tokens=8))

    comm.cdb = None
    e4 = deepspeed_tpu.init_inference(
        MoEGPT2(cfg, num_experts=8, ep_size=4),
        config={"dtype": "float32", "moe": {"ep_size": 4},
                "max_out_tokens": 128}, params=trained)
    assert e4.ep_world_size == 4
    # the serving expert bank is genuinely sharded over the expert axis
    wi = e4.params["moe"]["experts"]["wi"]   # (n_moe, E, D, H)
    assert wi.addressable_shards[0].data.shape[1] == wi.shape[1] // 4
    out4 = np.asarray(e4.generate(prompt, max_new_tokens=8))
    np.testing.assert_array_equal(out1, out4)
