"""Decode-attention kernel numerics (single-token KV-cache path).

Runs the Pallas TPU kernel in interpreter mode on the CPU mesh (bit-accurate
to the kernel's math); real-TPU numerics validated on hardware — see
.claude/skills/verify/SKILL.md.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu.ops.pallas.decode_attention as da


@pytest.fixture(autouse=True)
def _interpret_mode(monkeypatch):
    if jax.default_backend() != "tpu":
        from jax.experimental import pallas as pl

        monkeypatch.setattr(da.pl, "pallas_call",
                            functools.partial(pl.pallas_call, interpret=True))
    yield


def _rand(B, S, H, KV, Dh, seed=0):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(kq, (B, H, Dh), jnp.float32)
    k = jax.random.normal(kk, (B, S, KV, Dh), jnp.float32)
    v = jax.random.normal(kv, (B, S, KV, Dh), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("kv", [4, 2, 1])          # MHA, GQA, MQA
@pytest.mark.parametrize("pos", [0, 63, 64, 200, 255])
def test_matches_reference(kv, pos):
    B, S, H, Dh = 2, 256, 4, 64
    q, k, v = _rand(B, S, H, kv, Dh)
    out = da.decode_attention(q, k, v, jnp.int32(pos), block_k=64)
    ref = da.decode_reference(q, k, v, jnp.int32(pos))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_garbage_beyond_pos_ignored():
    """Entries past ``pos`` must not affect the output (the cache holds
    uninitialized zeros / stale tokens there)."""
    B, S, H, KV, Dh = 1, 128, 2, 1, 64
    q, k, v = _rand(B, S, H, KV, Dh, seed=1)
    pos = 40
    k_dirty = k.at[:, pos + 1:].set(1e9)
    v_dirty = v.at[:, pos + 1:].set(-1e9)
    out = da.decode_attention(q, k_dirty, v_dirty, jnp.int32(pos), block_k=32)
    ref = da.decode_reference(q, k, v, jnp.int32(pos))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_nondivisible_block_falls_back_to_divisor():
    B, S, H, KV, Dh = 1, 96, 4, 2, 32
    q, k, v = _rand(B, S, H, KV, Dh, seed=2)
    out = da.decode_attention(q, k, v, jnp.int32(95), block_k=64)  # 96 % 64 != 0
    ref = da.decode_reference(q, k, v, jnp.int32(95))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_model_decode_with_kernel_matches_einsum_path():
    """use_flash_decode=True must reproduce the default einsum decode through
    a whole LlamaModel decode_step (GQA cache, RoPE positions)."""
    import dataclasses

    from deepspeed_tpu.models.llama import PRESETS, LlamaModel

    base = dataclasses.replace(PRESETS["llama-tiny"], dtype=jnp.float32,
                               use_flash_attention=False, remat=False)
    m_ein = LlamaModel(base)
    m_ker = LlamaModel(dataclasses.replace(base, use_flash_decode=True))
    params = m_ein.init_params(jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.RandomState(0).randint(
        0, base.vocab_size, size=(2, 8)), jnp.int32)
    cache = m_ein.init_cache(2, 24)
    logits, cache = m_ein.prefill(params, ids, cache)
    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    out_e, _ = m_ein.decode_step(params, tok, cache)
    out_k, _ = m_ker.decode_step(params, tok, cache)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_e),
                               rtol=2e-4, atol=2e-4)


def test_bf16_inputs():
    B, S, H, KV, Dh = 2, 128, 4, 2, 64
    q, k, v = _rand(B, S, H, KV, Dh, seed=3)
    q, k, v = q.astype(jnp.bfloat16), k.astype(jnp.bfloat16), v.astype(jnp.bfloat16)
    out = da.decode_attention(q, k, v, jnp.int32(100))
    ref = da.decode_reference(q, k, v, jnp.int32(100))
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=3e-2, rtol=3e-2)
