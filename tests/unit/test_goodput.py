"""Goodput/badput accounting tests (ISSUE 8 acceptance surface).

The closed per-step ledger (partition sums to the wall window exactly,
priorities resolve overlaps), the engine meter behind the ``goodput``
ds_config block (series export, compile-span listener, strict no-op
without the block), cross-restart job reports (the synthetic two-session
fixture with an injected elastic restart must attribute the downtime to
the ``restart`` bucket), the tail-follower shared by ``ds_metrics
--follow`` and ``bin/ds_top``, the ``ds_prof merge`` degradation cases
(missing ranks, a restart mid-trace, empty/truncated files), the serving
request-span TTFT decomposition, and the bench --smoke goodput chain.
"""

import importlib.util
import io
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from deepspeed_tpu.goodput.ledger import (classify_window, goodput_fraction,
                                          load_trace_file, session_ledger,
                                          step_ledgers, step_windows,
                                          sum_buckets, top_badput)
from deepspeed_tpu.goodput.taxonomy import BUCKETS, GOODPUT_BUCKETS

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _span(name, ts, dur, cat="train", **args):
    return {"name": name, "cat": cat, "ph": "X", "ts": float(ts),
            "dur": float(dur), "pid": 0, "tid": 0, "args": args}


@pytest.mark.goodput
class TestTaxonomyLedger:
    def test_partition_sums_exactly_and_respects_priority(self):
        # a step: data wait, a train_batch envelope, a compile burst and a
        # comm span inside it, a checkpoint after it, idle at the end
        events = [
            _span("data", 0, 1000, step=0),
            _span("train_batch", 1000, 8000, step=0),
            _span("compile", 1500, 2000, cat="compile"),
            _span("all_reduce", 5000, 1000, cat="comm", op="all_reduce",
                  seq=0, group=""),
            _span("save_checkpoint", 9000, 500, cat="checkpoint"),
        ]
        window = (0.0, 10000.0)
        b = classify_window(events, window)
        assert abs(sum(b.values()) - 10000.0) < 1e-6
        assert b["data_wait"] == 1000.0
        # compile WINS over the enclosing train_batch (priority)
        assert b["compile"] == 2000.0
        # train_batch fully CONTAINS the comm span: it is an envelope
        # around a blocking collective, not overlapped compute — the comm
        # is exposed (same container-drop rule as FleetTrace)
        assert b["exposed_comm"] == 1000.0
        assert b["checkpoint"] == 500.0
        assert b["compute"] == 8000.0 - 2000.0 - 1000.0
        assert b["idle"] == 10000.0 - 1000.0 - 8000.0 - 500.0

    def test_exposed_comm_outside_compute(self):
        # comm sticking out past the compute span IS exposed
        events = [
            _span("train_batch", 0, 4000, step=0),
            _span("all_reduce", 3000, 3000, cat="comm", op="all_reduce",
                  seq=0, group=""),
        ]
        b = classify_window(events, (0.0, 6000.0))
        assert b["exposed_comm"] == 2000.0
        assert b["compute"] == 4000.0
        assert sum(b.values()) == 6000.0

    def test_watchdog_stall_wins_over_everything(self):
        events = [
            _span("train_batch", 0, 5000, step=0),
            _span("watchdog_stall", 1000, 3000, cat="stall"),
        ]
        b = classify_window(events, (0.0, 5000.0))
        assert b["watchdog_stall"] == 3000.0
        assert b["compute"] == 2000.0

    def test_step_windows_include_data_span(self):
        events = [
            _span("data", 100, 400, step=3),
            _span("train_batch", 500, 2000, step=3),
            _span("data", 2600, 100, step=4),
            _span("train_batch", 2700, 1800, step=4),
        ]
        ws = step_windows(events)
        assert ws == [(3, (100.0, 2500.0)), (4, (2600.0, 4500.0))]
        ledgers = step_ledgers(events)
        for led in ledgers:
            assert abs(sum(led["buckets"].values()) - led["wall_us"]) < 1e-6

    def test_straggler_intervals_claim_their_slot(self):
        events = [
            _span("train_batch", 0, 4000, step=0),
            _span("all_reduce", 3000, 3000, cat="comm", op="all_reduce",
                  seq=0, group=""),
        ]
        b = classify_window(events, (0.0, 6000.0),
                            straggler_intervals=[(4500.0, 6000.0)])
        # the tail of the exposed comm was really waiting for a straggler
        assert b["straggler_wait"] == 1500.0
        assert b["exposed_comm"] == 500.0
        assert sum(b.values()) == 6000.0

    def test_session_ledger_and_helpers(self):
        events = [
            _span("data", 0, 500, step=0),
            _span("train_batch", 500, 4500, step=0),
            _span("data", 6000, 500, step=1),
            _span("train_batch", 6500, 3500, step=1),
        ]
        led = session_ledger(events)
        assert led["wall_us"] == 10000.0
        assert abs(sum(led["buckets"].values()) - 10000.0) < 1e-6
        assert led["buckets"]["idle"] == 1000.0     # the inter-step gap
        assert len(led["steps"]) == 2
        gf = goodput_fraction(led["buckets"])
        assert gf == pytest.approx(0.8)
        assert top_badput(led["buckets"])[0] in ("idle", "data_wait")
        total = sum_buckets([led["buckets"], led["buckets"]])
        assert total["compute"] == 2 * led["buckets"]["compute"]


class _EngineMixin:
    def _engine(self, goodput=None, telemetry_cfg=None):
        import deepspeed_tpu
        from deepspeed_tpu.models.simple import SimpleModel

        cfg = {"train_batch_size": 8, "steps_per_print": 0,
               "optimizer": {"type": "adamw", "params": {"lr": 1e-3}}}
        if telemetry_cfg is not None:
            cfg["telemetry"] = telemetry_cfg
        if goodput is not None:
            cfg["goodput"] = goodput
        engine, *_ = deepspeed_tpu.initialize(
            model=SimpleModel(hidden_dim=16, nlayers=2), config=cfg)
        return engine

    @staticmethod
    def _batch(i=0):
        rng = np.random.RandomState(i)
        return (rng.randn(8, 16).astype(np.float32),
                rng.randn(8, 16).astype(np.float32))


@pytest.mark.goodput
class TestEngineGoodput(_EngineMixin):
    def test_series_exported_and_lag_one_step(self, tmp_path):
        from deepspeed_tpu import telemetry

        engine = self._engine(
            goodput={},
            telemetry_cfg={"enabled": True,
                           "output_dir": str(tmp_path / "t"),
                           "flush_interval": 1000})
        try:
            for i in range(4):
                engine.train_batch(self._batch(i))
            assert engine._goodput is not None
            by_name = {}
            for r in telemetry.get_registry().snapshot():
                key = (r["name"],) + tuple(sorted(
                    (r.get("labels") or {}).items()))
                by_name[key] = r
            # the live series lag one step: spans carry the PRE-increment
            # step counter (0..3 over 4 batches), and the 4th batch's
            # hook sees spans 0..2 complete (span 3 is still open)
            assert by_name[("goodput/step",)]["value"] == 2
            gf = by_name[("goodput/goodput_fraction",)]["value"]
            assert 0.0 < gf <= 1.0
            fr = {k[1][1]: v["value"] for k, v in by_name.items()
                  if k[0] == "goodput/fraction"}
            assert set(fr) == set(BUCKETS)
            assert abs(sum(fr.values()) - 1.0) < 1e-6
            assert by_name[("goodput/step_wall_s",)]["value"] > 0
            # no closure violations on a healthy run
            assert ("goodput/closure_violations",) not in by_name
        finally:
            telemetry.deconfigure()

    def test_compile_spans_stamped_by_listener(self, tmp_path):
        from deepspeed_tpu import telemetry

        engine = self._engine(
            goodput={},
            telemetry_cfg={"enabled": True,
                           "output_dir": str(tmp_path / "t"),
                           "flush_interval": 1000})
        try:
            engine.train_batch(self._batch())
            events = list(telemetry.get_session().tracer.events)
            compiles = [e for e in events if e.get("cat") == "compile"]
            assert compiles, "the jax.monitoring listener must stamp " \
                             "backend compiles as compile spans"
            assert all(e["name"] == "compile" for e in compiles)
        finally:
            telemetry.deconfigure()

    def test_attribution_closure_within_tolerance(self, tmp_path):
        """THE acceptance bound: every per-step breakdown's buckets sum to
        within 5% of the measured step wall time (data + train_batch
        window, measured independently from the raw spans)."""
        from deepspeed_tpu import telemetry

        engine = self._engine(
            goodput={},
            telemetry_cfg={"enabled": True,
                           "output_dir": str(tmp_path / "t"),
                           "flush_interval": 1000})
        try:
            for i in range(5):
                engine.train_batch(self._batch(i))
            events = list(telemetry.get_session().tracer.events)
            att = engine._goodput.attribution(events, timed_steps=3)
            assert att["goodput_fraction"] > 0
            assert len(att["per_step"]) == 3
            # independently measured step wall: the step's span extents
            by_step = {}
            for ev in events:
                step = (ev.get("args") or {}).get("step")
                if ev.get("ph") == "X" and isinstance(step, int) \
                        and ev.get("name") in ("data", "train_batch"):
                    lo, hi = by_step.get(step, (float("inf"), 0.0))
                    by_step[step] = (min(lo, ev["ts"]),
                                     max(hi, ev["ts"] + ev["dur"]))
            for led in att["per_step"]:
                total = sum(led["buckets_us"].values())
                assert total == pytest.approx(led["wall_us"], rel=1e-3)
                lo, hi = by_step[led["step"]]
                measured = hi - lo
                assert abs(total - measured) / measured < 0.05
        finally:
            telemetry.deconfigure()

    def test_strict_noop_without_block(self, tmp_path):
        """Without the ``goodput`` block the package is provably never
        imported and no meter exists (same contract as profiling/perf)."""
        mods = [m for m in list(sys.modules) if m.startswith("deepspeed_tpu.goodput")]
        saved = {m: sys.modules.pop(m) for m in mods}
        try:
            engine = self._engine(
                telemetry_cfg={"enabled": True,
                               "output_dir": str(tmp_path / "t"),
                               "flush_interval": 1000})
            engine.train_batch(self._batch())
            assert engine._goodput is None
            assert not any(m.startswith("deepspeed_tpu.goodput")
                           for m in sys.modules)
        finally:
            from deepspeed_tpu import telemetry

            telemetry.deconfigure()
            sys.modules.update(saved)

    def test_block_with_enabled_false_is_noop(self, tmp_path):
        engine = self._engine(goodput={"enabled": False})
        engine.train_batch(self._batch())
        assert engine._goodput is None


@pytest.mark.goodput
class TestSessionAnchors:
    def test_tracer_metadata_carries_clock_anchor(self):
        from deepspeed_tpu.telemetry.tracing import StepTracer

        before = time.time()
        tr = StepTracer(pid=3)
        after = time.time()
        meta = tr.to_chrome_trace()["metadata"]
        anchor = meta["clock_anchor"]
        assert before <= anchor["epoch_s"] <= after
        assert "monotonic_s" in anchor
        assert meta["rank"] == 3

    def test_new_session_rotates_stale_trace(self, tmp_path):
        from deepspeed_tpu import telemetry
        from deepspeed_tpu.runtime.config import TelemetryConfig

        out = str(tmp_path / "t")
        cfg = TelemetryConfig(enabled=True, output_dir=out,
                              flush_interval=1000, prometheus=False)
        s1 = telemetry.configure(cfg)
        try:
            with s1.tracer.span("train_batch", step=0):
                pass
            s1.flush()
            assert os.path.exists(os.path.join(out, "trace.json"))
            s2 = telemetry.configure(cfg)      # restart: same dir
            with s2.tracer.span("train_batch", step=0):
                pass
            s2.flush()
        finally:
            telemetry.deconfigure()
        assert os.path.exists(os.path.join(out, "trace.json"))
        assert os.path.exists(os.path.join(out, "trace.session1.json"))
        a1 = load_trace_file(os.path.join(out, "trace.session1.json"))
        a2 = load_trace_file(os.path.join(out, "trace.json"))
        assert a1["anchor_epoch_s"] is not None
        assert a2["anchor_epoch_s"] >= a1["anchor_epoch_s"]


# --------------------------------------------------------------- job report
def _session_trace(rank, epoch0, spans, extra_meta=None):
    events = [{"name": "process_name", "ph": "M", "pid": rank, "tid": 0,
               "args": {"name": f"deepspeed_tpu rank {rank}"}}]
    events += spans
    meta = {"rank": rank, "dropped_events": 0,
            "clock_anchor": {"epoch_s": epoch0, "monotonic_s": 0.0}}
    meta.update(extra_meta or {})
    return {"traceEvents": events, "displayTimeUnit": "ms", "metadata": meta}


def _steps(n, start_us=0.0, step_us=100_000.0, first_step=0):
    spans = []
    t = start_us
    for i in range(n):
        spans.append(_span("data", t, 2000, step=first_step + i))
        spans.append(_span("train_batch", t + 2000, step_us - 2000,
                           step=first_step + i))
        t += step_us
    return spans


@pytest.mark.goodput
class TestJobReport:
    def test_two_session_restart_downtime_attributed(self, tmp_path):
        """The acceptance fixture: one rank, an elastic restart with 5 s
        of downtime between two sessions — the job report must charge the
        gap to the ``restart`` bucket and name the restart reason."""
        from deepspeed_tpu.goodput.report import (build_job_report,
                                                  render_goodput_report)

        t0 = 1_700_000_000.0
        s1 = tmp_path / "trace.session1.json"
        s2 = tmp_path / "trace.json"
        # session 1: 2 steps over 0.2 s, then the job dies; session 2
        # starts 5 s after session 1's last span ends
        s1.write_text(json.dumps(_session_trace(0, t0, _steps(2))))
        s2.write_text(json.dumps(_session_trace(
            0, t0 + 0.2 + 5.0, _steps(2, first_step=2))))
        rlog = tmp_path / "restart_log.jsonl"
        rlog.write_text(json.dumps(
            {"restart": 1, "error": "WatchdogTimeout: step 2 hung",
             "step": 2, "backoff_s": 1.0, "ts": t0 + 2.0}) + "\n")
        from deepspeed_tpu.goodput.report import load_restart_log

        report = build_job_report([str(s1), str(s2)],
                                  restart_log=load_restart_log([str(tmp_path)]))
        assert report["ranks"] == [0]
        assert report["sessions"] == 2
        b = report["buckets_s"]
        assert b["restart"] == pytest.approx(5.0, rel=0.01)
        assert b["compute"] == pytest.approx(4 * 0.098, rel=0.01)
        assert report["restarts"][0]["reasons"] == \
            ["WatchdogTimeout: step 2 hung"]
        # ledger closes: fleet seconds == sum of buckets
        assert sum(b.values()) == pytest.approx(report["fleet_seconds"])
        text = render_goodput_report(report)
        assert "restart" in text and "WatchdogTimeout" in text
        assert "goodput:" in text

    def test_background_span_does_not_stretch_session_or_gap(self, tmp_path):
        """A background async-checkpoint commit span that outlives the
        step loop must not define the session's extent: pre-fix it
        stretched session 1 into the restart gap, compressed the charged
        downtime to ~0 and pushed the restart record outside the match
        window — silently dropping the resize annotation THE drill
        asserts on."""
        from deepspeed_tpu.goodput.report import (build_job_report,
                                                  render_goodput_report)

        t0 = 1_700_000_000.0
        spans = _steps(2)
        # commit thread finishes 4.8 s into the 5 s restart gap
        spans.append(_span("save_checkpoint", 150_000, 4_850_000,
                           cat="checkpoint", background=True))
        s1 = tmp_path / "trace.session1.json"
        s2 = tmp_path / "trace.json"
        s1.write_text(json.dumps(_session_trace(0, t0, spans)))
        s2.write_text(json.dumps(_session_trace(
            0, t0 + 0.2 + 5.0, _steps(2, first_step=2))))
        rlog = [{"restart": 1, "error": "FleetResizeEvent: fleet shrink",
                 "ts": t0 + 0.25, "tier": "ram", "snapshot_step": 2,
                 "steps_lost": 1, "restore_s": 0.01, "reshard_s": 0.01,
                 "resize": {"kind": "shrink", "from_world": 8,
                            "to_world": 6}}]
        report = build_job_report([str(s1), str(s2)], restart_log=rlog)
        assert report["buckets_s"]["restart"] == pytest.approx(5.0, rel=0.01)
        assert report["restarts"][0]["reasons"] == \
            ["FleetResizeEvent: fleet shrink"]
        text = render_goodput_report(report)
        assert "shrink 8->6 resharded" in text

    def test_unmatched_record_attaches_to_nearest_gap(self, tmp_path):
        """A restart record whose ts misses every gap's exact window
        (anchor wobble, a late flush) still annotates the nearest gap —
        loudly — instead of vanishing from the report."""
        from deepspeed_tpu.goodput.report import (build_job_report,
                                                  render_goodput_report)

        t0 = 1_700_000_000.0
        s1 = tmp_path / "trace.session1.json"
        s2 = tmp_path / "trace.json"
        s1.write_text(json.dumps(_session_trace(0, t0, _steps(2))))
        s2.write_text(json.dumps(_session_trace(
            0, t0 + 0.2 + 5.0, _steps(20, first_step=2))))
        # stamped 2.5 s AFTER session 2 began (a slow restore): outside
        # the gap's +1 s window, inside the 30 s nearest-gap slack
        rlog = [{"restart": 1, "error": "resume from disk tier",
                 "ts": t0 + 5.2 + 2.5, "tier": "disk", "snapshot_step": 2,
                 "steps_lost": 0, "restore_s": 2.4}]
        report = build_job_report([str(s1), str(s2)], restart_log=rlog)
        assert report["restarts"][0]["reasons"] == ["resume from disk tier"]
        assert report["restarts"][0]["recoveries"][0]["tier"] == "disk"
        assert any("nearest gap" in w for w in report["warnings"])
        assert "disk tier" in render_goodput_report(report)

    def test_missing_anchor_degrades_loudly(self, tmp_path):
        from deepspeed_tpu.goodput.report import build_job_report

        s1 = tmp_path / "a.json"
        s2 = tmp_path / "b.json"
        t1 = _session_trace(0, 100.0, _steps(1))
        t2 = _session_trace(0, 0.0, _steps(1))
        del t2["metadata"]["clock_anchor"]
        s1.write_text(json.dumps(t1))
        s2.write_text(json.dumps(t2))
        report = build_job_report([str(s1), str(s2)])
        assert report["buckets_s"]["restart"] == 0.0
        assert any("clock anchor" in w for w in report["warnings"])

    def test_fleet_straggler_attribution(self, tmp_path):
        from deepspeed_tpu.goodput.report import build_job_report

        t0 = 1_700_000_000.0
        comm0 = [_span("all_reduce", 50_000, 40_000, cat="comm",
                       op="all_reduce", seq=0, group="")]
        comm1 = [_span("all_reduce", 80_000, 10_000, cat="comm",
                       op="all_reduce", seq=0, group="")]
        p0 = tmp_path / "trace.json"
        p1 = tmp_path / "trace.rank1.json"
        p0.write_text(json.dumps(_session_trace(
            0, t0, _steps(1) + comm0)))
        p1.write_text(json.dumps(_session_trace(
            1, t0, _steps(1) + comm1)))
        report = build_job_report([str(p0), str(p1)])
        # rank 0 arrived 30 ms early -> it waited for the straggler
        r0 = report["per_rank"][0]["buckets_us"]
        assert r0["straggler_wait"] == pytest.approx(30_000, rel=0.01)
        assert report["per_rank"][1]["buckets_us"]["straggler_wait"] == 0.0

    def test_ds_prof_goodput_cli(self, tmp_path, capsys):
        from deepspeed_tpu.profiling.cli import main

        t0 = 1_700_000_000.0
        (tmp_path / "trace.session1.json").write_text(
            json.dumps(_session_trace(0, t0, _steps(2))))
        (tmp_path / "trace.json").write_text(
            json.dumps(_session_trace(0, t0 + 0.2 + 3.0,
                                      _steps(2, first_step=2))))
        (tmp_path / "restart_log.jsonl").write_text(json.dumps(
            {"restart": 1, "error": "BadStepError: loss blew up",
             "ts": t0 + 1.0}) + "\n")
        assert main(["goodput", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "restart" in out and "BadStepError" in out
        assert main(["goodput", str(tmp_path), "--json"]) == 0
        data = json.loads(capsys.readouterr().out)
        assert data["buckets_s"]["restart"] == pytest.approx(3.0, rel=0.01)

    def test_empty_dir_fails_loudly(self, tmp_path):
        from deepspeed_tpu.profiling.cli import main

        assert main(["goodput", str(tmp_path)]) == 2


# ------------------------------------------------------------------ tailers
@pytest.mark.goodput
class TestTailers:
    def test_tailer_appends_torn_lines_truncation(self, tmp_path):
        from deepspeed_tpu.goodput.tail import JSONLTailer

        p = tmp_path / "m.jsonl"
        t = JSONLTailer(str(p))
        assert t.poll() == []                      # not created yet
        with open(p, "w") as f:
            f.write('{"a": 1}\n{"a": 2}\n')
        assert [r["a"] for r in t.poll()] == [1, 2]
        assert t.poll() == []
        with open(p, "a") as f:
            f.write('{"a": 3')                     # torn mid-append
        assert t.poll() == []                      # waits for the newline
        with open(p, "a") as f:
            f.write('}\n')
        assert [r["a"] for r in t.poll()] == [3]
        # truncation: a fresh run reuses the path
        with open(p, "w") as f:
            f.write('{"b": 1}\n')
        recs = t.poll()
        assert [r.get("b") for r in recs] == [1]
        assert t.resets == 1
        # rotation: new inode at the same path
        os.replace(str(tmp_path / "m.jsonl"), str(tmp_path / "old"))
        with open(p, "w") as f:
            f.write('{"c": 1}\nnot json\n')
        recs = t.poll()
        assert [r.get("c") for r in recs] == [1]
        assert t.bad_lines == 1

    def test_metrics_follower_keeps_last_per_series(self, tmp_path):
        from deepspeed_tpu.goodput.tail import MetricsFollower

        p = tmp_path / "m.jsonl"
        f = MetricsFollower(str(p))
        rec = {"kind": "gauge", "name": "train/loss", "labels": {},
               "value": 5.0, "ts": 1.0, "step": 1}
        with open(p, "w") as fh:
            fh.write(json.dumps(rec) + "\n")
            fh.write(json.dumps(dict(rec, value=3.0, step=2)) + "\n")
        assert f.poll() is True
        [r] = f.records()
        assert r["value"] == 3.0 and r["step"] == 2
        assert f.poll() is False

    def test_ds_metrics_follow(self, tmp_path):
        import importlib.machinery

        loader = importlib.machinery.SourceFileLoader(
            "_ds_metrics_test", os.path.join(REPO, "bin", "ds_metrics"))
        spec = importlib.util.spec_from_loader(loader.name, loader)
        mod = importlib.util.module_from_spec(spec)
        loader.exec_module(mod)
        p = tmp_path / "metrics.jsonl"
        with open(p, "w") as f:
            f.write(json.dumps({"kind": "gauge", "name": "train/loss",
                                "labels": {}, "value": 2.5, "ts": 1.0,
                                "step": 7}) + "\n")
        out = io.StringIO()
        assert mod.follow(str(p), interval=0.01, max_polls=2, out=out) == 0
        text = out.getvalue()
        assert "telemetry summary" in text and "train/loss" in text

    def test_ds_top_once_cli(self, tmp_path):
        p = tmp_path / "metrics.jsonl"
        recs = [
            {"kind": "gauge", "name": "goodput/goodput_fraction",
             "labels": {}, "value": 0.82, "ts": time.time(), "step": 12},
            {"kind": "gauge", "name": "goodput/step_wall_s", "labels": {},
             "value": 0.5, "ts": time.time(), "step": 12},
            {"kind": "gauge", "name": "goodput/fraction",
             "labels": {"bucket": "exposed_comm"}, "value": 0.18,
             "ts": time.time(), "step": 12},
            {"kind": "gauge", "name": "train/samples_per_sec",
             "labels": {}, "value": 42.0, "ts": time.time(), "step": 12},
        ]
        with open(p, "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bin", "ds_top"),
             str(tmp_path), "--once"],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr
        assert "goodput  82.0%" in proc.stdout
        assert "exposed_comm 18.0%" in proc.stdout
        assert "step 12" in proc.stdout

    def test_render_frame_serving_line(self):
        from deepspeed_tpu.goodput.top import render_frame

        now = time.time()
        recs = [
            {"kind": "gauge", "name": "serving/state", "labels": {},
             "value": 1, "ts": now, "step": None},
            {"kind": "gauge", "name": "serving/queue_depth", "labels": {},
             "value": 3, "ts": now, "step": None},
            {"kind": "histogram", "name": "serving/ttft_seconds",
             "labels": {}, "count": 5, "p50": 0.2, "p90": 0.4, "p99": 0.5,
             "max": 0.6, "sum": 1.0, "min": 0.1, "ts": now, "step": None},
            {"kind": "counter", "name": "serving/shed",
             "labels": {"reason": "queue_full"}, "value": 2, "ts": now,
             "step": None},
        ]
        frame = render_frame(recs, source="x")
        assert "serving: ready" in frame
        assert "queue 3" in frame
        assert "ttft p50 0.2s" in frame
        assert "shed 2" in frame


# ------------------------------------------------------- ds_prof merge gaps
@pytest.mark.goodput
class TestMergeDegradation:
    def test_missing_rank_warns(self, tmp_path):
        from deepspeed_tpu.profiling.aggregate import FleetTrace

        for rank in (0, 2):
            (tmp_path / f"trace.rank{rank}.json").write_text(
                json.dumps(_session_trace(rank, 0.0, _steps(1))))
        ft = FleetTrace.from_files(
            [str(tmp_path / "trace.rank0.json"),
             str(tmp_path / "trace.rank2.json")])
        assert sorted(ft.by_rank) == [0, 2]
        assert any("missing rank" in w and "1" in w for w in ft.warnings)

    def test_two_files_one_rank_is_loud_error(self, tmp_path):
        from deepspeed_tpu.profiling.aggregate import FleetTrace

        a = tmp_path / "a.json"
        b = tmp_path / "b.json"
        a.write_text(json.dumps(_session_trace(0, 0.0, _steps(1))))
        b.write_text(json.dumps(_session_trace(0, 0.0, _steps(1))))
        with pytest.raises(ValueError, match="rank 0"):
            FleetTrace.from_files([str(a), str(b)])

    def test_restart_mid_trace_excluded_from_matching(self, tmp_path):
        """A rank whose trace holds TWO sessions (elastic restart: the
        per-session seq counters reset, so identities repeat) must not
        phantom-match the other ranks — duplicated identities are dropped
        from alignment/straggler analysis, loudly."""
        from deepspeed_tpu.profiling.aggregate import FleetTrace

        comm = lambda ts: _span("all_reduce", ts, 1000, cat="comm",
                                op="all_reduce", seq=0, group="")
        restarted = _session_trace(0, 0.0, [comm(1000), comm(500_000)])
        healthy = _session_trace(1, 0.0, [comm(1000)])
        a = tmp_path / "trace.json"
        b = tmp_path / "trace.rank1.json"
        a.write_text(json.dumps(restarted))
        b.write_text(json.dumps(healthy))
        ft = FleetTrace.from_files([str(a), str(b)])
        assert ft.collective_matches() == []
        assert ft.straggler_table() == []       # no fabricated straggler
        assert any("more than once" in w for w in ft.warnings)
        assert ft.clock_offsets() == {0: 0.0, 1: 0.0}

    def test_empty_and_truncated_files(self, tmp_path, capsys):
        from deepspeed_tpu.profiling.aggregate import FleetTrace
        from deepspeed_tpu.profiling.cli import main

        empty = tmp_path / "trace.rank1.json"
        empty.write_text("")
        good = tmp_path / "trace.json"
        good.write_text(json.dumps(_session_trace(0, 0.0, _steps(1))))
        torn = tmp_path / "trace.rank2.jsonl"
        with open(torn, "w") as f:
            f.write(json.dumps(_span("train_batch", 0, 1000, step=0)) + "\n")
            f.write('{"name": "tr')            # killed mid-append
        ft = FleetTrace.from_files([str(good), str(empty), str(torn)])
        assert sorted(ft.by_rank) == [0, 2]    # no phantom lane for rank 1
        assert any("empty trace" in w for w in ft.warnings)
        assert any("torn" in w for w in ft.warnings)
        # the CLI surfaces the warnings on stderr and still merges
        assert main(["merge", str(tmp_path)]) == 0
        err = capsys.readouterr().err
        assert "empty trace" in err and "torn" in err

    def test_merge_dir_scan_excludes_rotated_sessions(self, tmp_path,
                                                      capsys):
        from deepspeed_tpu.profiling.cli import main

        # a restart left two sessions of rank 0 in the dir; merge must
        # scan only the live trace.json, not die on a two-claims error
        (tmp_path / "trace.session1.json").write_text(
            json.dumps(_session_trace(0, 0.0, _steps(1))))
        (tmp_path / "trace.json").write_text(
            json.dumps(_session_trace(0, 10.0, _steps(1, first_step=1))))
        assert main(["merge", str(tmp_path), "--json"]) == 0
        data = json.loads(capsys.readouterr().out)
        assert data["ranks"] == [0]


# --------------------------------------------------------- serving spans
@pytest.mark.goodput
class TestServingRequestSpans:
    def test_ttft_decomposition_series(self, tmp_path):
        from deepspeed_tpu import telemetry
        from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
        from deepspeed_tpu.inference.engine import InferenceEngine
        from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
        from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                                  TelemetryConfig)
        from deepspeed_tpu.serving import ServingFrontEnd

        cfg = GPT2Config(vocab_size=64, n_positions=32, n_embd=32,
                         n_layer=1, n_head=2)
        engine = InferenceEngine(
            GPT2Model(cfg),
            DeepSpeedInferenceConfig(dtype="float32", max_out_tokens=16))
        tel = telemetry.configure(TelemetryConfig(
            enabled=True, output_dir=str(tmp_path / "t"),
            flush_interval=1000, prometheus=False))
        ds = DeepSpeedConfig({"serving": {"decode_tick_tokens": 4,
                                          "max_queue_depth": 4}})
        fe = ServingFrontEnd(engine, ds.serving, start=True)
        try:
            prompt = (np.arange(4)[None, :] % 64).astype(np.int32)
            r = fe.submit(prompt, max_new_tokens=4)
            r.result(timeout=300)
            assert r.status == "completed"
            names = {rec["name"] for rec in tel.registry.snapshot()}
            assert "serving/prefill_seconds" in names
            assert "serving/decode_chunk_seconds" in names
            assert "serving/queue_wait_seconds" in names
            spans = [e for e in tel.tracer.events
                     if e.get("cat") == "serving"]
            by_name = {e["name"] for e in spans}
            assert {"admission_wait", "prefill", "decode"} <= by_name
            assert all((e.get("args") or {}).get("request") == r.id
                       for e in spans)
            # the SLO renderer decomposes TTFT from the new series
            from deepspeed_tpu.profiling.report import \
                render_serving_summary

            text = render_serving_summary(
                [rec for rec in tel.registry.snapshot()
                 if rec["name"].startswith("serving/")])
            assert "prefill_seconds" in text
            assert "ttft decomposition" in text
        finally:
            fe.close()
            telemetry.deconfigure()


# ------------------------------------------------------------- schema/gate
@pytest.mark.goodput
class TestSchemaAndGate:
    def test_top_level_did_you_mean(self):
        from deepspeed_tpu.runtime.config import DeepSpeedConfig

        with pytest.raises(ValueError, match="goodput"):
            DeepSpeedConfig({"train_batch_size": 8, "goodputt": {}})

    def test_unknown_key_inside_block(self):
        from deepspeed_tpu.runtime.config import GoodputConfig

        with pytest.raises(Exception, match="compile_spans"):
            GoodputConfig(compile_span=True)

    def test_schema_pass_goodput_without_telemetry(self):
        from deepspeed_tpu.analysis.schema import walk_config

        findings, _ = walk_config({"train_batch_size": 8, "goodput": {}})
        msgs = [f.message for f in findings]
        assert any("goodput is enabled without telemetry" in m for m in msgs)
        findings, _ = walk_config({"train_batch_size": 8, "goodput": {},
                                   "telemetry": {"enabled": True}})
        msgs = [f.message for f in findings]
        assert not any("goodput is enabled without" in m for m in msgs)

    def test_gate_fails_on_goodput_regression(self, tmp_path):
        from deepspeed_tpu.perf import ledger as led
        from deepspeed_tpu.perf.cli import main

        entry = {"metric": "m pretrain MFU (x)", "value": 0.5,
                 "unit": "MFU", "model": "m", "fingerprint": "f",
                 "headline": True, "goodput_fraction": 0.9}
        base = str(tmp_path / "base.jsonl")
        cand = str(tmp_path / "cand.jsonl")
        led.append_entry(base, dict(entry))
        # headline value holds, goodput collapses -> gate must fail
        led.append_entry(cand, dict(entry, goodput_fraction=0.6))
        assert main(["gate", "--baseline", base, "--candidate", cand]) == 2
        # both fine -> pass
        cand2 = str(tmp_path / "cand2.jsonl")
        led.append_entry(cand2, dict(entry, goodput_fraction=0.89))
        assert main(["gate", "--baseline", base, "--candidate", cand2]) == 0

    def test_compare_reports_goodput_fields(self):
        from deepspeed_tpu.perf import ledger as led

        old = {"metric": "m (x)", "value": 1.0, "goodput_fraction": 0.8}
        new = {"metric": "m (x)", "value": 1.0, "goodput_fraction": 0.7}
        r = led.compare(old, new)
        assert r["old_goodput"] == 0.8 and r["new_goodput"] == 0.7
        assert r["goodput_regressed"] is True
        assert r["verdict"] == "within_noise"   # headline itself held


@pytest.mark.goodput
class TestBenchSmokeGoodput:
    """The --smoke acceptance: every ledger entry carries a per-step
    goodput breakdown whose buckets sum to within 5% of the measured
    step wall time, and the hoisted goodput_fraction is gateable."""

    @pytest.fixture(scope="class")
    def smoke(self, tmp_path_factory):
        tmp = tmp_path_factory.mktemp("goodput_smoke")
        ledger = str(tmp / "ledger.jsonl")
        env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_SEQ="64",
                   BENCH_TELEMETRY_DIR=str(tmp / "telemetry"))
        env.pop("XLA_FLAGS", None)
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"), "--smoke",
             "--ledger", ledger],
            capture_output=True, text=True, timeout=420, env=env, cwd=tmp)
        return proc, ledger

    def test_entry_carries_closed_goodput_breakdown(self, smoke):
        from deepspeed_tpu.perf import ledger as led

        proc, ledger = smoke
        assert proc.returncode == 0, proc.stderr[-2000:]
        [entry] = led.load_entries(ledger)
        gp = entry["attribution"]["goodput"]
        assert gp["per_step"], "every entry must carry per-step ledgers"
        for step in gp["per_step"]:
            total = sum(step["buckets_us"].values())
            assert abs(total - step["wall_us"]) / step["wall_us"] < 0.05
        assert 0.0 < gp["goodput_fraction"] <= 1.0
        assert entry["goodput_fraction"] == gp["goodput_fraction"]
        # the per-step wall windows agree with the independently recorded
        # train_batch samples (seconds) to the acceptance tolerance plus
        # the data-wait the window includes
        assert len(entry["samples"]) >= len(gp["per_step"])
        # the stderr note is the human surface bench prints
        assert "# goodput:" in proc.stderr

    def test_goodput_fraction_gates(self, smoke, tmp_path):
        from deepspeed_tpu.perf import ledger as led
        from deepspeed_tpu.perf.cli import main

        proc, ledger = smoke
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert main(["gate", "--baseline", ledger,
                     "--candidate", ledger]) == 0
        [entry] = led.load_entries(ledger)
        # synthetic candidate whose headline holds but whose goodput
        # collapsed to half — per-step ledgers scaled consistently, so
        # the t gate sees a REAL step-level collapse (matching per-step
        # evidence would rightly exonerate an aggregate-only blip)
        cand = str(tmp_path / "cand.jsonl")
        synthetic = json.loads(json.dumps(
            {k: v for k, v in entry.items() if k != "samples"}))
        synthetic["goodput_fraction"] = entry["goodput_fraction"] * 0.5
        for s in synthetic["attribution"]["goodput"]["per_step"]:
            compute = s["buckets_us"].get("compute", 0.0) * 0.5
            s["buckets_us"]["compute"] = compute
            s["buckets_us"]["idle"] = s["wall_us"] - sum(
                v for k, v in s["buckets_us"].items() if k != "idle")
        led.append_entry(cand, synthetic)
        assert main(["gate", "--baseline", ledger,
                     "--candidate", cand]) == 2
