"""Block-sparse attention tests (reference tests/unit/ops/sparse_attention
role): layout builders + sparse flash kernel numerics vs the dense-masked
oracle, fwd and bwd, causal and not."""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu.ops.pallas.flash_attention as fa
from deepspeed_tpu.ops.sparse_attention import (BigBirdSparsityConfig,
                                                BSLongformerSparsityConfig,
                                                DenseSparsityConfig,
                                                FixedSparsityConfig,
                                                SparseSelfAttention,
                                                VariableSparsityConfig,
                                                flash_attention_sparse,
                                                sparse_mha_reference)


@pytest.fixture(autouse=True)
def _interpret_mode(monkeypatch):
    if jax.default_backend() != "tpu":
        from jax.experimental import pallas as pl

        monkeypatch.setattr(fa.pl, "pallas_call",
                            functools.partial(pl.pallas_call, interpret=True))
    yield


class TestLayouts:
    def test_dense(self):
        lay = DenseSparsityConfig(num_heads=4, block=16).make_layout(64)
        assert lay.shape == (4, 4) and lay.all()

    def test_fixed_local_plus_global(self):
        cfg = FixedSparsityConfig(num_heads=4, block=16, num_local_blocks=2,
                                  num_global_blocks=1)
        lay = cfg.make_layout(128)   # 8 blocks
        assert lay.shape == (8, 8)
        assert lay[0, 0] and lay[0, 1]        # local window
        assert not lay[0, 2]                  # outside window, not global
        assert lay[:, 1].all()                # global col (last of window 0)

    def test_bigbird_window_global_random(self):
        cfg = BigBirdSparsityConfig(num_heads=4, block=16,
                                    num_sliding_window_blocks=3,
                                    num_global_blocks=1, num_random_blocks=1)
        lay = cfg.make_layout(128)
        n = lay.shape[0]
        assert all(lay[i, i] for i in range(n))     # window includes self
        assert lay[:, 0].all() and lay[0, :].all()  # global

    def test_longformer(self):
        cfg = BSLongformerSparsityConfig(num_heads=4, block=16,
                                         num_sliding_window_blocks=3,
                                         global_block_indices=[0])
        lay = cfg.make_layout(128)
        assert lay[:, 0].all() and lay[0, :].all()
        assert lay[4, 3] and lay[4, 5] and not lay[4, 6]

    def test_local_sliding_window(self):
        from deepspeed_tpu.ops.sparse_attention import \
            LocalSlidingWindowSparsityConfig

        # unidirectional (the reference default): causal half-window only
        cfg = LocalSlidingWindowSparsityConfig(num_heads=4, block=16,
                                               num_sliding_window_blocks=3)
        lay = cfg.make_layout(128)
        assert lay[4, 3] and lay[4, 4]
        assert not lay[4, 5]                   # future blocked
        assert not lay[4, 2]                   # past the window
        assert not lay[:, 0].all()             # NO global columns
        bi = LocalSlidingWindowSparsityConfig(
            num_heads=4, block=16, num_sliding_window_blocks=3,
            attention="bidirectional").make_layout(128)
        assert bi[4, 5] and not bi[4, 6]

    def test_variable(self):
        cfg = VariableSparsityConfig(num_heads=4, block=16,
                                     local_window_blocks=[2, 3],
                                     global_block_indices=[0])
        lay = cfg.make_layout(160)
        assert lay[:, 0].all()

    def test_indivisible_seq_raises(self):
        with pytest.raises(ValueError, match="divisible"):
            FixedSparsityConfig(num_heads=2, block=16).make_layout(100)


def _qkv(B=1, T=128, H=2, D=64, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return [jax.random.normal(k, (B, T, H, D), jnp.float32) for k in ks]


class TestSparseKernel:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense_oracle(self, causal):
        q, k, v = _qkv()
        cfg = BigBirdSparsityConfig(num_heads=2, block=32,
                                    num_sliding_window_blocks=3,
                                    num_global_blocks=1, num_random_blocks=1)
        lay = cfg.make_layout(128)
        out = flash_attention_sparse(q, k, v, lay, causal=causal)
        ref = sparse_mha_reference(q, k, v, lay, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-2, rtol=2e-2)

    def test_dense_layout_equals_flash(self):
        q, k, v = _qkv(seed=1)
        lay = DenseSparsityConfig(num_heads=2, block=32).make_layout(128)
        out = flash_attention_sparse(q, k, v, lay, causal=True)
        ref = fa.mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-2, rtol=2e-2)

    @pytest.mark.parametrize("causal", [True, False])
    def test_gradients_match_oracle(self, causal):
        q, k, v = _qkv(T=64, seed=2)
        cfg = BSLongformerSparsityConfig(num_heads=2, block=32,
                                         num_sliding_window_blocks=1,
                                         global_block_indices=[0])
        lay = cfg.make_layout(64)

        def loss_sparse(q, k, v):
            return (flash_attention_sparse(q, k, v, lay, causal=causal)
                    .astype(jnp.float32) * jnp.arange(64)).sum()

        def loss_ref(q, k, v):
            return (sparse_mha_reference(q, k, v, lay, causal=causal)
                    .astype(jnp.float32) * jnp.arange(64)).sum()

        g1 = jax.grad(loss_sparse, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-2, rtol=5e-2)

    def test_empty_key_column_grads_are_zero(self):
        """Key blocks nobody attends must get exactly-zero dk/dv (dummy-pair
        finalization), not garbage."""
        q, k, v = _qkv(T=64, seed=3)
        lay = np.zeros((2, 2), dtype=bool)
        lay[0, 0] = lay[1, 0] = True            # both rows attend col 0 ONLY
        # → key column 1 is attended by nobody: its dk/dv must be exact zeros
        gk, gv = jax.grad(
            lambda k_, v_: flash_attention_sparse(q, k_, v_, lay, causal=True)
            .astype(jnp.float32).sum(), argnums=(0, 1))(k, v)
        gk, gv = np.asarray(gk), np.asarray(gv)
        assert np.isfinite(gk).all() and np.isfinite(gv).all()
        np.testing.assert_array_equal(gk[:, 32:], 0.0)
        np.testing.assert_array_equal(gv[:, 32:], 0.0)
        assert np.abs(gv[:, :32]).max() > 0

    def test_sparse_self_attention_module(self):
        q, k, v = _qkv(T=128, seed=4)
        mod = SparseSelfAttention(FixedSparsityConfig(num_heads=2, block=32,
                                                      num_local_blocks=2))
        out = mod(q, k, v, causal=True)
        assert out.shape == q.shape
        ref = sparse_mha_reference(q, k, v, mod.get_layout(128), causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-2, rtol=2e-2)

    def test_empty_query_row_raises(self):
        q, k, v = _qkv(T=64, seed=5)
        lay = np.zeros((2, 2), dtype=bool)
        lay[0, 0] = True                        # row 1 attends to nothing
        with pytest.raises(ValueError, match="no key blocks"):
            flash_attention_sparse(q, k, v, lay, causal=True)


class TestModelSparseAttention:
    """sparse_attention wired end-to-end: ds_config block → model dispatch
    (reference flow: "sparse_attention" JSON + SparseAttentionUtils patch)."""

    def test_ds_config_block_reaches_model_and_trains(self):
        import deepspeed_tpu
        from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model

        cfg = GPT2Config(vocab_size=128, n_positions=64, n_embd=32, n_layer=2,
                         n_head=2, use_flash_attention=False, remat=False)
        model = GPT2Model(cfg)
        engine, *_ = deepspeed_tpu.initialize(
            model=model,
            config={"train_batch_size": 8,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                    "bf16": {"enabled": True},
                    "zero_optimization": {"stage": 1},
                    "sparse_attention": {"mode": "fixed", "block": 16,
                                         "num_local_blocks": 2,
                                         "num_global_blocks": 1},
                    "steps_per_print": 0})
        assert model.config.sparse_attention["mode"] == "fixed"
        rng = np.random.RandomState(0)
        batch = {"input_ids": rng.randint(0, 128, size=(8, 64)).astype(np.int32)}
        losses = [float(engine.train_batch(batch)) for _ in range(6)]
        assert losses[-1] < losses[0], losses

    def test_sparse_model_masks_distant_tokens(self):
        """A local-window-only layout must make far-away keys invisible:
        perturbing a token outside every window of the last query cannot
        change the last-position logits (it CAN under dense attention)."""
        import dataclasses

        from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model

        cfg = GPT2Config(vocab_size=64, n_positions=64, n_embd=32, n_layer=1,
                         n_head=2, dtype=jnp.float32, use_flash_attention=False,
                         remat=False,
                         sparse_attention={"mode": "fixed", "block": 16,
                                           "num_local_blocks": 1,
                                           "num_global_blocks": 0})
        model = GPT2Model(cfg)
        params = model.init_params(jax.random.PRNGKey(0))
        rng = np.random.RandomState(1)
        ids = rng.randint(0, 64, size=(1, 64)).astype(np.int32)
        far = ids.copy()
        far[0, 20] = (far[0, 20] + 1) % 64    # block 1 — outside q-block 3's window
        out = np.asarray(model.apply(params, jnp.asarray(ids)))[0, -1]
        out_far = np.asarray(model.apply(params, jnp.asarray(far)))[0, -1]
        np.testing.assert_array_equal(out, out_far)

        dense = GPT2Model(dataclasses.replace(cfg, sparse_attention=None))
        d = np.asarray(dense.apply(params, jnp.asarray(ids)))[0, -1]
        d_far = np.asarray(dense.apply(params, jnp.asarray(far)))[0, -1]
        assert np.abs(d - d_far).max() > 0
