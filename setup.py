"""Package build for deepspeed_tpu (reference: setup.py at the repo root).

Also builds the native C++ extension(s) registered by the op registry
(deepspeed_tpu/ops/op_builder.py) — currently the async file-I/O library used
for host/NVMe offload. Pure-Python install works without a toolchain; the
native libs are JIT-built on first use otherwise.
"""

import os

from setuptools import find_packages, setup

ROOT = os.path.dirname(os.path.abspath(__file__))


def _version():
    with open(os.path.join(ROOT, "deepspeed_tpu", "__init__.py")) as f:
        for line in f:
            if line.startswith("__version__"):
                return line.split("=")[1].strip().strip('"')
    return "0.0.0"


setup(
    name="deepspeed_tpu",
    version=_version(),
    description="TPU-native training/inference framework with DeepSpeed's capabilities",
    packages=find_packages(include=["deepspeed_tpu", "deepspeed_tpu.*"]),
    python_requires=">=3.10",
    install_requires=["jax", "flax", "optax", "orbax-checkpoint", "numpy", "pydantic>=2"],
    scripts=["bin/deepspeed_tpu", "bin/ds_report", "bin/ds_bench", "bin/ds_elastic", "bin/ds_doctor"],
)
